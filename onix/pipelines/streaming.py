"""Streaming scoring: online-VB LDA over ingest minibatches.

Covers BASELINE.json configs[4] ("streaming online-VB LDA over
oni-ingest minibatches (incremental scoring)") — a capability the
reference does NOT have: oni-lda-c re-fits from scratch once per day
(SURVEY.md §3.1), so a beacon that starts at 09:00 is invisible until
the next day's batch run. onix scores each ingest minibatch the moment
it lands, against a model updated by every batch seen so far.

Streaming-specific design (vs the batch path in pipelines/run.py):

- **Hashed vocabulary.** A batch run fits its vocabulary after seeing
  the whole day; a stream never sees "the whole day". Words hash into a
  fixed number of buckets, so the topic-word parameter lambda [V,K] has
  a static shape forever — the XLA-friendly rendering of an unbounded
  vocabulary. Buckets come from a vectorized splitmix64 over the packed
  int64 `word_key` (`_bucket_of_keys`) — process-stable (unlike
  Python's salted hash) and with no per-row or per-unique string work;
  collisions merge rare words into shared buckets, which for a rarity
  detector is conservative (a colliding rare word can only look MORE
  common, never less).
- **Frozen bin edges.** Quantile edges are fitted on the first batch
  (or a warmup batch) and applied verbatim afterwards; re-fitting per
  batch would silently redefine every word mid-stream.
- **Bounded document table.** IPs get dense doc ids on first sight;
  the per-doc gamma store grows by powers of two so the scoring step
  compiles O(log D) times, not O(batches). With `max_docs` set, the
  least-recently-seen quarter is evicted (and ids compacted) whenever
  the population crosses the bound, so a stream that lives for months
  holds — and checkpoints — O(max_docs) per-doc state, not O(every IP
  ever seen).
- **Static shapes.** Token and doc axes of every minibatch are padded
  to powers of two — a stream of irregular batches reuses a handful of
  compiled programs (asserted in tests).
- **Device-resident word creation (default).** Once the edges freeze,
  each columnar minibatch's binning → packed-key build → splitmix64
  bucketing runs as ONE fused device program (device_words.py
  `*_stream_buckets`): the int64 word key is packed in uint32 limbs and
  hashed with 32-bit limb arithmetic, so buckets are IDENTICAL to the
  host `_bucket_of_keys` (given identical bin indices; f32-vs-f64 edge
  comparisons can differ ~1e-7/event — device_words docstring). The
  per-unique string features (dns/proxy) stay host-side per refresh.
  The tables are rebuilt from the frozen edges per batch only where
  they depend on the batch (caller proto order, the batch's unique
  string values) — O(uniques), not O(events).
- **Deduped weighted E-step.** The minibatch fed to SVI is the UNIQUE
  (doc, bucket) pairs with their counts as token weights
  (`make_minibatch(weights=...)`): every E-step/λ-step contribution
  multiplies by the weight, so the math is exactly the repeated-token
  update at a fraction of the [T,K] passes (telemetry is Zipf — unique
  pairs run 4-5x below the token count). Scoring broadcasts the
  unique-pair scores back through the inverse index, so per-event
  scores and alerts are unchanged in meaning.
- **Escape hatch.** ONIX_HOST_WORDS=1 pins the host reference path
  (word builders + host hash + undeduped E-step) — the cross-check arm
  measurements compare against. The host path also catches everything
  the device path declines: the first batch (edges still fitting),
  string/IPv6 doc keys, non-power-of-two bucket counts, and frames the
  columnar converter rejects.
"""

from __future__ import annotations

import dataclasses
import hashlib
import pathlib
import time

import numpy as np
import pandas as pd

from onix.config import OnixConfig
from onix.models.lda_svi import SVILda, SVIState, make_minibatch, phi_estimate
from onix.models.scoring import score_all
from onix.pipelines.words import WORD_FNS
from onix.utils import resilience
from onix.utils.obs import counters


def _next_pow2(n: int, floor: int = 256) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p


def _bucket_of_keys(word_keys: np.ndarray, salt: int,
                    n_buckets: int) -> np.ndarray:
    """Packed int64 word keys → stable bucket ids, fully vectorized.

    The r03 scorer rendered every word to its display STRING and
    blake2b-hashed the unique strings per batch — measured as a top
    host cost of the 58k ev/s streaming wall (VERDICT r03 weak #6).
    Every word path (string or columnar) already carries the packed
    integer `word_key`, and rendering is a bijection given frozen
    edges, so hashing the key is the same identity at none of the
    string cost. splitmix64 finalizer: deterministic across processes
    (unlike Python's salted hash), full-avalanche, one vector pass."""
    x = word_keys.astype(np.uint64) ^ np.uint64(salt)
    x = (x + np.uint64(0x9E3779B97F4A7C15)) & np.uint64(0xFFFFFFFFFFFFFFFF)
    x ^= x >> np.uint64(30)
    x = (x * np.uint64(0xBF58476D1CE4E5B9)) & np.uint64(0xFFFFFFFFFFFFFFFF)
    x ^= x >> np.uint64(27)
    x = (x * np.uint64(0x94D049BB133111EB)) & np.uint64(0xFFFFFFFFFFFFFFFF)
    x ^= x >> np.uint64(31)
    return (x % np.uint64(n_buckets)).astype(np.int32)


def _datatype_salt(datatype: str) -> int:
    """Stable per-datatype hash salt (keys of different datatypes must
    not systematically collide into the same buckets)."""
    return int.from_bytes(
        hashlib.blake2b(datatype.encode(), digest_size=8).digest(),
        "little")


class DocTable:
    """IP string → dense doc id, first-seen order.

    Growth is bounded by the owner (StreamingScorer evicts idle docs
    via `compact`); `load` restores a saved key list in one bulk pass —
    the round-2 restore replayed checkpointed IPs one at a time, which
    at the reference's ~10⁶-IP scale took minutes (VERDICT r2 weak #8).
    """

    def __init__(self):
        self._index: dict[str, int] = {}
        self.keys: list[str] = []

    @property
    def n_docs(self) -> int:
        return len(self.keys)

    def ids(self, ips: np.ndarray) -> np.ndarray:
        uniq, inv = np.unique(np.asarray(ips, dtype=object), return_inverse=True)
        out = np.empty(len(uniq), np.int32)
        for i, ip in enumerate(uniq):
            idx = self._index.get(ip)
            if idx is None:
                idx = len(self.keys)
                self._index[ip] = idx
                self.keys.append(ip)
            out[i] = idx
        return out[inv]

    def load(self, keys) -> None:
        """Bulk-replace the table (vectorized restore path)."""
        self.keys = [str(k) for k in keys]
        self._index = {k: i for i, k in enumerate(self.keys)}

    def compact(self, keep_mask: np.ndarray) -> np.ndarray:
        """Drop docs where ~keep_mask; survivors keep first-seen order
        with new dense ids. Returns the OLD ids of the survivors (the
        gather index for any id-parallel array, e.g. gamma rows)."""
        keep_idx = np.flatnonzero(keep_mask)
        self.keys = [self.keys[i] for i in keep_idx]
        self._index = {k: i for i, k in enumerate(self.keys)}
        return keep_idx


class U32DocTable:
    """uint32 IP → dense doc id, first-seen order — the integer twin of
    DocTable for the columnar streaming path (no per-row IP strings
    anywhere in the hot loop). `keys` is a uint32 array; `as_strings()`
    renders dotted-quads for the one-way conversion to string mode when
    a stream hits a non-columnar batch mid-flight (canonical v4 strings
    are the same doc identities, so the switch is lossless)."""

    def __init__(self):
        self._index: dict[int, int] = {}
        self.keys = np.zeros(0, np.uint32)

    @property
    def n_docs(self) -> int:
        return len(self.keys)

    def ids(self, ips_u32: np.ndarray) -> np.ndarray:
        uniq, inv = np.unique(np.asarray(ips_u32, np.uint32),
                              return_inverse=True)
        out = np.empty(len(uniq), np.int32)
        fresh = []
        n = len(self.keys)
        for i, ip in enumerate(uniq.tolist()):
            idx = self._index.get(ip)
            if idx is None:
                idx = n + len(fresh)
                self._index[ip] = idx
                fresh.append(ip)
            out[i] = idx
        if fresh:
            self.keys = np.concatenate(
                [self.keys, np.asarray(fresh, np.uint32)])
        return out[inv]

    def load(self, keys) -> None:
        self.keys = np.asarray(keys, np.uint32)
        self._index = {int(k): i for i, k in enumerate(self.keys.tolist())}

    def compact(self, keep_mask: np.ndarray) -> np.ndarray:
        keep_idx = np.flatnonzero(keep_mask)
        self.keys = self.keys[keep_idx]
        self._index = {int(k): i for i, k in enumerate(self.keys.tolist())}
        return keep_idx

    def as_strings(self) -> list[str]:
        from onix.pipelines.words import u32_to_ips
        return u32_to_ips(self.keys).tolist()


@dataclasses.dataclass
class BatchResult:
    """Incremental scoring output for one minibatch."""

    scores: np.ndarray        # float64 [n_events] per-event score
    alerts: pd.DataFrame      # events with score < tol, ascending, enriched
    n_events: int
    n_new_docs: int
    step: int                 # global SVI step after this batch


class StreamingScorer:
    """Online-VB LDA fed by ingest minibatches, scoring as it goes.

    Usage: one instance per datatype stream; call `process(table)` for
    each decoded minibatch (a file, a Kafka-equivalent queue drain, a
    store partition slice). Returns per-event scores plus the alert rows
    under `tol`."""

    def __init__(self, cfg: OnixConfig, datatype: str,
                 n_buckets: int = 1 << 15,
                 checkpoint_dir: str | None = None, resume: bool = True,
                 max_docs: int | None = None):
        cfg.validate()
        if n_buckets < 2:
            raise ValueError("n_buckets must be >= 2")
        self.cfg = cfg
        self.datatype = datatype
        self.n_buckets = int(n_buckets)
        self._salt = _datatype_salt(datatype)
        # Integer-keyed doc table while every batch goes columnar; a
        # one-way switch to the string table happens on the first batch
        # the columnar converter rejects (e.g. IPv6 strings).
        self.docs: U32DocTable | DocTable = U32DocTable()
        self.word_fn = WORD_FNS[datatype]
        self.edges: dict | None = None
        self.model = SVILda(cfg.lda, n_buckets, corpus_docs=1)
        self.state: SVIState = self.model.init()
        k = cfg.lda.n_topics
        self._gamma = np.full((_next_pow2(1), k), cfg.lda.alpha, np.float32)
        # Eviction bound on per-doc state: a long-lived stream sees an
        # unbounded IP population, so gamma/doc-table growth must have a
        # ceiling. When n_docs crosses `max_docs`, the least-recently-
        # seen quarter is dropped (an evicted IP that returns restarts
        # from the prior — for a rarity detector that direction is
        # conservative: a fresh doc's uniform theta cannot make its
        # events look rarer than history would).
        self.max_docs = max_docs
        self._last_seen = np.zeros(self._gamma.shape[0], np.int64)
        self.pad_shapes: set[tuple[int, int]] = set()   # compile accounting
        # Cumulative per-stage walls (seconds) — the r03 streaming rate
        # was 300x under the batch scan with the host path unprofiled
        # (VERDICT r03 weak #6); every artifact now carries the split.
        # prefetch_overlap/prefetch_wait account the one-deep conversion
        # prefetch (ColumnPrefetcher): overlap = frame→columns seconds
        # that ran hidden under the previous batch's step, wait = the
        # residual the consumer still blocked on.
        self.stage_walls = {"words": 0.0, "ids": 0.0, "minibatch": 0.0,
                            "svi_update": 0.0, "score": 0.0, "emit": 0.0,
                            "prefetch_overlap": 0.0, "prefetch_wait": 0.0}
        # Which word path each batch rode (device fused vs host
        # reference) — artifacts report it next to the stage walls.
        self.words_mode_batches = {"device": 0, "host": 0}
        self._batch_no = 0
        self.checkpoint_dir = (pathlib.Path(checkpoint_dir)
                               if checkpoint_dir else None)
        if self.checkpoint_dir is not None and resume:
            self._restore_latest()

    # -- checkpoint / resume (SURVEY.md §5.3-5.4) -------------------------
    #
    # A preempted stream must not lose the model: round 1 held SVIState,
    # hashed-vocab params, DocTable, gamma, and the frozen edges purely
    # in memory — the exact failure checkpointing exists to prevent.
    # Everything needed to continue (and to score identically) persists
    # every `lda.checkpoint_every` batches.

    def _fingerprint(self) -> str:
        from onix import checkpoint as ckpt

        # checkpoint.fingerprint's sampling fields are Gibbs-oriented;
        # the SVI schedule knobs change what this engine computes, so a
        # checkpoint under a different schedule must not be adopted.
        lda = self.cfg.lda
        # layout=3: word buckets hash the packed word_key (splitmix64),
        # not the rendered string (blake2b) — a lambda trained under the
        # old scheme addresses different buckets and must not be adopted.
        return ckpt.fingerprint(
            lda, 0, self.n_buckets, 0,
            extra={"stream_datatype": self.datatype,
                   "n_buckets": self.n_buckets,
                   # meanchange joined when the E-step gained the
                   # convergence stop: a lambda trained under a
                   # different local-iteration rule is a different
                   # model and must not be adopted.
                   "svi": [lda.svi_tau0, lda.svi_kappa,
                           lda.svi_local_iters, lda.svi_meanchange_tol],
                   "layout": 3})

    def save_checkpoint(self) -> None:
        from onix import checkpoint as ckpt
        if self.checkpoint_dir is None:
            return
        edges = None
        if self.edges is not None:
            edges = {k: (v if isinstance(v, list) else np.asarray(v).tolist())
                     for k, v in self.edges.items()}
        n = self.docs.n_docs
        # Per-doc state goes in the npz as COLUMNS trimmed to n_docs —
        # round 2 serialized every IP string into the JSON meta (tens of
        # MB at 10⁶ docs) and saved gamma at padded capacity. The doc
        # key column matches the live table mode: a raw uint32 array on
        # the columnar path (4 B/doc), utf-8 strings otherwise.
        u32_mode = isinstance(self.docs, U32DocTable)
        doc_keys = (self.docs.keys if u32_mode else np.char.encode(
            np.asarray(self.docs.keys, dtype=str), "utf-8"))
        ckpt.save(
            self.checkpoint_dir / self._fingerprint(), self._batch_no,
            {"lam": np.asarray(self.state.lam),
             "step": np.asarray(self.state.step),
             "gamma": self._gamma[:n],
             "doc_keys": doc_keys,
             "last_seen": self._last_seen[:n]},
            {"fingerprint": self._fingerprint(), "engine": "streaming",
             "datatype": self.datatype, "doc_key_mode":
                 "u32" if u32_mode else "str",
             "edges": edges})

    def _restore_latest(self) -> bool:
        import jax.numpy as jnp

        from onix import checkpoint as ckpt
        saved = ckpt.load_latest(self.checkpoint_dir / self._fingerprint())
        if saved is None or saved.meta.get("fingerprint") != self._fingerprint():
            return False
        self.state = SVIState(lam=jnp.asarray(saved.arrays["lam"]),
                              step=jnp.asarray(saved.arrays["step"]))
        if saved.meta.get("doc_key_mode", "str") == "u32":
            self.docs = U32DocTable()
            self.docs.load(saved.arrays["doc_keys"])
        else:
            self.docs = DocTable()
            self.docs.load(np.char.decode(saved.arrays["doc_keys"],
                                          "utf-8"))
        n = self.docs.n_docs
        cap = _next_pow2(max(n, 1))
        k = saved.arrays["gamma"].shape[1]
        self._gamma = np.full((cap, k), self.cfg.lda.alpha, np.float32)
        self._gamma[:n] = saved.arrays["gamma"]
        self._last_seen = np.zeros(cap, np.int64)
        self._last_seen[:n] = saved.arrays["last_seen"]
        edges = saved.meta.get("edges")
        self.edges = ({k: (v if isinstance(v, list) and v
                           and isinstance(v[0], str) else np.asarray(v))
                       for k, v in edges.items()}
                      if edges is not None else None)
        self._batch_no = saved.sweep
        return True

    # -- internals --------------------------------------------------------

    def _grow(self, n_docs: int) -> None:
        cap = self._gamma.shape[0]
        if n_docs <= cap:
            return
        new_cap = _next_pow2(n_docs, floor=cap)
        grown = np.full((new_cap, self._gamma.shape[1]),
                        self.cfg.lda.alpha, np.float32)
        grown[:cap] = self._gamma
        self._gamma = grown
        seen = np.zeros(new_cap, np.int64)
        seen[:cap] = self._last_seen
        self._last_seen = seen

    def _maybe_evict(self) -> int:
        """Keep the doc population under `max_docs`: when crossed, drop
        the least-recently-seen quarter and compact ids/gamma/last_seen
        so the stream's per-doc state (and its checkpoints) stay
        bounded no matter how many distinct IPs it ever sees."""
        if self.max_docs is None or self.docs.n_docs <= self.max_docs:
            return 0
        n = self.docs.n_docs
        target = max(1, int(self.max_docs * 0.75))
        # Survivors = the `target` most recently seen (ties broken by
        # doc id: older docs go first, matching LRU intent).
        order = np.lexsort((np.arange(n), -self._last_seen[:n]))
        keep = np.zeros(n, bool)
        keep[order[:target]] = True
        old_ids = self.docs.compact(keep)
        n_new = len(old_ids)
        cap = _next_pow2(max(n_new, 1))
        gamma = np.full((cap, self._gamma.shape[1]),
                        self.cfg.lda.alpha, np.float32)
        gamma[:n_new] = self._gamma[old_ids]
        seen = np.zeros(cap, np.int64)
        seen[:n_new] = self._last_seen[old_ids]
        self._gamma, self._last_seen = gamma, seen
        return n - n_new

    # -- the streaming step -----------------------------------------------

    def convert_columns(self, table: pd.DataFrame) -> dict | None:
        """frame → numeric columns, or None for frames the converter
        rejects (malformed columns — those ride the string word path).

        Pure host work on an immutable frame with NO scorer state read
        or written (the columnar converters don't need the bin edges),
        so it is safe to run on a prefetch thread while the previous
        batch's device step occupies the main thread — ColumnPrefetcher
        does exactly that and `process(table, cols=...)` consumes the
        result without re-converting."""
        from onix.pipelines import columnar

        conv = columnar.FRAME_COLS[self.datatype]
        try:
            return conv(table)
        except (ValueError, KeyError):
            return None

    def _words(self, table: pd.DataFrame, cols: dict | None = None):
        """One minibatch → WordTable, columnar-first.

        The frame converters do the per-UNIQUE-value string work and the
        *_words_from_arrays builders everything per-row in NumPy — the
        same machinery as the batch scale runner. IPv6/non-canonical
        addresses ride the tagged-u64 dictionary (words.IP_TAG), which
        has no uint32 doc keys — such batches flip the doc table
        one-way to string keys (same raw-string identities). A frame
        the converter rejects outright (malformed columns) falls back
        to the string word path; word identity is unaffected either
        way (both paths emit the same packed word_key)."""
        from onix.pipelines import columnar

        if cols is None:
            cols = self.convert_columns(table)
        if cols is None:
            return self.word_fn(table, edges=self.edges)
        return columnar.words_from_cols(self.datatype, cols,
                                        edges=self.edges)

    def _device_words(self, table: pd.DataFrame,
                      cols: dict | None = None):
        """Fused device word path for one minibatch: columnar convert
        (host, per-unique string work — prefetchable, see
        convert_columns) → ONE jitted program for binning + key packing
        + splitmix64 bucketing. Returns (bucket ids [T], ip_u32 [T],
        event_idx [T]) in the host token layout, or None when the batch
        must ride the host path (docstring list)."""
        import jax.numpy as jnp

        from onix.pipelines import device_words as dw

        if cols is None:
            cols = self.convert_columns(table)
        if cols is None:
            return None
        if "ip_table" in cols:      # IPv6/non-canonical: string doc keys
            return None
        n = len(table)
        pad = _next_pow2(n)

        def _cols(names, dtypes):
            # Pow2-pad the per-event columns so the jitted bucket
            # program compiles once per SIZE CLASS, not once per batch
            # length (the module's static-shape contract; through the
            # TPU tunnel a retrace costs 5-30 s). Zero padding is safe:
            # every program is elementwise and row 0 of each gathered
            # table exists; the pad rows are sliced off below.
            return [jnp.asarray(np.pad(np.asarray(cols[c], d),
                                       (0, pad - n)))
                    for c, d in zip(names, dtypes)]

        if self.datatype == "flow":
            t = dw.build_flow_stream_tables(
                self.edges, list(cols["proto_classes"]))
            wid_e = np.asarray(dw.flow_stream_buckets(
                t, *_cols(("sport", "dport", "proto_id", "hour", "ibyt",
                           "ipkt"),
                          (np.int32, np.int32, np.int32, np.float32,
                           np.float32, np.float32)),
                salt=self._salt, n_buckets=self.n_buckets))[:n]
            ev = np.arange(n, dtype=np.int64)
            return (np.concatenate([wid_e, wid_e]),
                    np.concatenate([cols["sip_u32"], cols["dip_u32"]]),
                    np.concatenate([ev, ev]))
        if self.datatype == "dns":
            t = dw.build_dns_stream_tables(self.edges, cols["qnames"])
            wid = np.asarray(dw.dns_stream_buckets(
                t, *_cols(("qname_codes", "qtype", "rcode", "frame_len",
                           "hour"),
                          (np.int32, np.int32, np.int32, np.float32,
                           np.float32)),
                salt=self._salt, n_buckets=self.n_buckets))[:n]
        else:
            t = dw.build_proxy_stream_tables(
                self.edges, cols["uris"], cols["hosts"], cols["agents"])
            wid = np.asarray(dw.proxy_stream_buckets(
                t, *_cols(("uri_codes", "host_codes", "ua_codes",
                           "respcode", "hour"),
                          (np.int32, np.int32, np.int32, np.int32,
                           np.float32)),
                salt=self._salt, n_buckets=self.n_buckets))[:n]
        return (wid, np.asarray(cols["client_u32"], np.uint32),
                np.arange(n, dtype=np.int64))

    def _device_eligible(self) -> bool:
        from onix.pipelines.device_words import host_words_forced

        return (self.edges is not None                   # frozen
                and isinstance(self.docs, U32DocTable)
                and self.n_buckets & (self.n_buckets - 1) == 0
                and not host_words_forced())

    def process(self, table: pd.DataFrame,
                cols: dict | None = None) -> BatchResult:
        """Word-create, model-update, and score one minibatch.

        `cols` takes a pre-converted column dict from convert_columns
        (the ColumnPrefetcher hands it over) so the ~30%-of-batch-wall
        frame→columns host conversion (docs/PERF.md r6) that already ran
        under the previous batch's device step is not paid again.

        Chaos hook: a `stream:batch` rule in the active fault plan
        fires HERE, before any scorer state (model, doc table, gamma,
        batch counter) is touched — so a caller that retries the batch
        (run_stream does, bounded) replays it against unchanged state
        and the stream's artifacts are identical to a fault-free run."""
        from onix.utils import faults

        faults.fire("stream", "batch")
        n_events = len(table)
        if n_events == 0:
            return BatchResult(np.empty(0), table.iloc[0:0].copy(), 0, 0,
                               int(self.state.step))
        t_stage = time.perf_counter
        t0 = t_stage()
        dev = (self._device_words(table, cols)
               if self._device_eligible() else None)
        if dev is None:
            words = self._words(table, cols)
            if self.edges is None:
                self.edges = words.edges   # frozen from the first batch on
        self.words_mode_batches["host" if dev is None else "device"] += 1
        self.stage_walls["words"] += t_stage() - t0

        t0 = t_stage()
        docs_before = self.docs.n_docs
        if dev is not None:
            wid, ip_u32, event_idx = dev
            did = self.docs.ids(ip_u32)
        else:
            # Buckets from the packed integer keys — no per-row (or even
            # per-unique) string rendering in the hot loop.
            wid = _bucket_of_keys(words.word_key, self._salt,
                                  self.n_buckets)
            event_idx = words.event_idx
            if words.ip_u32 is not None and isinstance(self.docs,
                                                       U32DocTable):
                did = self.docs.ids(words.ip_u32)
            else:
                if isinstance(self.docs, U32DocTable):
                    # First non-columnar batch: convert to string keys
                    # once (canonical v4 — identical doc identities).
                    str_table = DocTable()
                    str_table.load(self.docs.as_strings())
                    self.docs = str_table
                did = self.docs.ids(words.ip)
        self._grow(self.docs.n_docs)
        self.stage_walls["ids"] += t_stage() - t0

        t0 = t_stage()
        t = len(wid)
        inv = None
        from onix.pipelines.device_words import host_words_forced
        if not host_words_forced():
            # Unique (doc, bucket) pairs with counts: the E-step and
            # scoring run over U << T weighted rows; `inv` broadcasts
            # pair scores back to tokens (MiniBatch mask semantics).
            # Independent of the word path — a host-words batch (edges
            # still fitting, IPv6, rejected frame) still dedups.
            pair = did.astype(np.int64) * self.n_buckets + wid
            uniq, inv, cnt = np.unique(pair, return_inverse=True,
                                       return_counts=True)
            did_b = (uniq // self.n_buckets).astype(np.int32)
            wid_b = (uniq % self.n_buckets).astype(np.int32)
            weights = cnt.astype(np.float32)
            t_rows = len(uniq)
        else:
            did_b, wid_b, weights, t_rows = did, wid, None, t
        n_batch_docs = len(np.unique(did_b))
        pad_to = _next_pow2(t_rows)
        pad_docs = _next_pow2(n_batch_docs, floor=64)
        self.pad_shapes.add((pad_to, pad_docs))
        batch = make_minibatch(did_b, wid_b, pad_to=pad_to,
                               pad_docs=pad_docs, weights=weights)
        dm = np.asarray(batch.doc_map)
        real = dm >= 0
        # Warm-start the E-step from each returning doc's LAST gamma —
        # recurring docs (the stream's common case) converge in a few
        # iterations under the meanchange stop instead of re-walking
        # from the prior every batch. First-seen docs start cold.
        k = self._gamma.shape[1]
        g0 = np.full((batch.n_docs, k), self.cfg.lda.alpha + 1.0,
                     np.float32)
        prev = real.copy()
        prev[real] = dm[real] < docs_before
        g0[prev] = self._gamma[dm[prev]]
        self.stage_walls["minibatch"] += t_stage() - t0

        t0 = t_stage()
        # Corpus-size estimate for the natural-gradient scale: the docs
        # seen so far (the standard running-D choice for streams).
        self.state, gamma = self.model.update(
            self.state, batch, corpus_docs=max(self.docs.n_docs, 2),
            gamma0=g0)
        gm = np.asarray(gamma)
        self.stage_walls["svi_update"] += t_stage() - t0
        self._gamma[dm[real]] = gm[real]
        self._last_seen[dm[real]] = self._batch_no + 1

        # Incremental scoring of THIS batch's events under the updated
        # model. Only the batch's OWN doc rows are normalized and
        # shipped — the full padded-capacity gamma grows with every doc
        # the stream has ever seen, so using it here would make each
        # batch cost O(total docs) on a long-running stream. Rows are
        # padded to the batch's pow2 doc shape (never-indexed filler at
        # the uniform prior), so the scoring program still compiles
        # once per (token, doc) shape pair, not per batch.
        # dm[real] is the batch's sorted unique global doc ids, and the
        # batch's padded local doc/word id arrays are exactly the token
        # columns scoring needs — make_minibatch already computed all of
        # them; no second unique pass over the tokens.
        t0 = t_stage()
        uniq_d = dm[real]
        k = self._gamma.shape[1]
        theta_b = np.full((pad_docs, k), 1.0 / k, np.float32)
        rows = self._gamma[uniq_d]
        theta_b[:len(uniq_d)] = rows / rows.sum(1, keepdims=True)
        if inv is not None:
            # One fused gather-dot program over the unique pairs, then
            # broadcast through the inverse — identical event scores at
            # a fraction of the gathered rows. phi stays device-side.
            import jax.numpy as jnp

            from onix.models.scoring import _score_events_jit
            pair_scores = np.asarray(_score_events_jit(
                jnp.asarray(theta_b), phi_estimate(self.state),
                batch.doc_ids, batch.word_ids))[:t_rows]
            tok_scores = pair_scores[inv]
        else:
            phi = np.asarray(phi_estimate(self.state))
            tok_scores = score_all(theta_b, phi, np.asarray(batch.doc_ids),
                                   np.asarray(batch.word_ids),
                                   chunk=pad_to)[:t]
        self.stage_walls["score"] += t_stage() - t0

        t0 = t_stage()
        if dev is not None and self.datatype == "flow":
            # Device flow layout is [src|dst] tokens of the same events
            # in order: the event min is one elementwise minimum, not an
            # unbuffered scatter.
            ev_scores = np.minimum(tok_scores[:n_events],
                                   tok_scores[n_events:]).astype(np.float64)
        else:
            ev_scores = np.full(n_events, np.inf, np.float64)
            np.minimum.at(ev_scores, event_idx, tok_scores)

        tol = self.cfg.pipeline.tol
        hit = np.flatnonzero(ev_scores < tol)
        hit = hit[np.argsort(ev_scores[hit], kind="stable")]
        hit = hit[: self.cfg.pipeline.max_results]
        alerts = table.iloc[hit].copy()
        alerts.insert(0, "score", ev_scores[hit])
        alerts.insert(1, "event_idx", hit)

        self._batch_no += 1
        self._maybe_evict()
        self.stage_walls["emit"] += t_stage() - t0
        every = self.cfg.lda.checkpoint_every
        if (self.checkpoint_dir is not None and every > 0
                and self._batch_no % every == 0):
            self.save_checkpoint()

        return BatchResult(scores=ev_scores, alerts=alerts,
                           n_events=n_events,
                           n_new_docs=self.docs.n_docs - docs_before,
                           step=int(self.state.step))


class ColumnPrefetcher:
    """One-deep prefetch of the frame→columns host conversion.

    The steady-state streaming batch spends ~30% of its wall in the
    frame→columns conversion (docs/PERF.md r6) — pure host string/array
    work that needs no scorer state — while the SVI/scoring step holds
    the device. This iterator runs the NEXT batch's conversion (and,
    when the source items are callables, its decode too) on a single
    worker thread while the caller processes the current one, mirroring
    the double-buffered `device_put` chunk staging in scale.py's
    _stream_score. One-deep by design: peak memory stays at two frames.

    `items` yields either DataFrames or zero-arg callables returning
    DataFrames (the callable form moves file decode into the worker).
    Yields (table, cols) pairs for `scorer.process(table, cols=cols)`;
    cols is None for frames the converter rejects (the host word path
    picks those up exactly as before). Overlap accounting lands in
    scorer.stage_walls: "prefetch_overlap" is conversion wall hidden
    under the previous batch, "prefetch_wait" the residual blocked on.
    """

    def __init__(self, scorer: StreamingScorer, items):
        self.scorer = scorer
        self.items = items

    def __iter__(self):
        import concurrent.futures as cf

        def produce(item):
            table = item() if callable(item) else item
            t0 = time.perf_counter()
            cols = self.scorer.convert_columns(table)
            return table, cols, time.perf_counter() - t0

        with cf.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="onix-prefetch") as pool:
            fut = None
            for item in self.items:
                nxt = pool.submit(produce, item)
                if fut is not None:
                    yield self._resolve(fut)
                fut = nxt
            if fut is not None:
                yield self._resolve(fut)

    def _resolve(self, fut):
        t0 = time.perf_counter()
        table, cols, conv_wall = fut.result()
        wait = time.perf_counter() - t0
        walls = self.scorer.stage_walls
        walls["prefetch_wait"] += wait
        walls["prefetch_overlap"] += max(conv_wall - wait, 0.0)
        return table, cols


def run_stream(cfg: OnixConfig, datatype: str, paths: list[str],
               n_buckets: int = 1 << 15, epochs: int = 1) -> int:
    """CLI driver: each raw telemetry file is one minibatch — decode,
    update the model, score, append alerts to a per-day streaming CSV.
    Decode + frame→columns conversion of batch i+1 overlap batch i's
    model step via the one-deep ColumnPrefetcher.

    `epochs > 1` replays the file list (useful to burn in a model before
    leaving it running on live data)."""
    from onix.ingest.run import decode
    from onix.store import results_path

    ck_dir = None
    if cfg.lda.checkpoint_every > 0:
        ck_dir = (pathlib.Path(cfg.store.checkpoint_dir) / datatype
                  / "stream")
    scorer = StreamingScorer(cfg, datatype, n_buckets=n_buckets,
                             checkpoint_dir=ck_dir,
                             max_docs=cfg.pipeline.stream_max_docs or None)
    total_events = 0
    total_alerts = 0
    # Resume skips batches the restored checkpoint already consumed —
    # re-processing them would double-train the model AND re-append
    # their alert rows to the per-day CSVs.
    done = scorer._batch_no
    if done:
        print(f"stream resume: skipping {done} already-processed batches")

    def batches():
        """(epoch, path, decode-thunk) for every batch left to process;
        the thunk runs on the prefetch worker, so file decode rides
        under the previous batch's step too."""
        batch_idx = 0
        for epoch in range(epochs):
            for p in paths:
                batch_idx += 1
                if batch_idx <= done:
                    continue
                yield (epoch, p,
                       lambda p=p: decode(
                           datatype, p,
                           apply_sampling=cfg.ingest.apply_sampling))

    todo = list(batches())
    prefetched = ColumnPrefetcher(scorer, (thunk for _, _, thunk in todo))
    # Injected batch faults (the chaos drill) are retried under the
    # shared bounded policy. The retry is restricted to InjectedFault
    # BY DESIGN: the fault hook fires at process() entry before any
    # scorer state mutates, so a replay is exact — whereas an arbitrary
    # mid-process error (device OOM during the SVI step) could land
    # after the model/doc-table updates and a blind replay would
    # double-train the batch. Real errors propagate: streams fail
    # loudly, they neither skip telemetry nor double-apply it.
    from onix.utils.faults import InjectedFault
    batch_policy = resilience.RetryPolicy(max_attempts=3,
                                          base_backoff_s=0.05,
                                          max_backoff_s=2.0,
                                          salvage_on_final=False)
    for (epoch, p, _), (table, cols) in zip(todo, prefetched):
        res = resilience.retry_call(
            lambda strict: scorer.process(table, cols=cols),
            policy=batch_policy, counter_prefix="stream.batch",
            retry_on=InjectedFault)
        total_events += res.n_events
        if epoch == epochs - 1 and len(res.alerts):
            # Alerts land in per-day files keyed like batch results.
            from onix.ingest.run import _day_of
            for date, rows in res.alerts.groupby(
                    _day_of(datatype, res.alerts)):
                out = results_path(cfg.store.results_dir, datatype,
                                   str(date))
                out = out.with_name(f"{datatype}_streaming.csv")
                out.parent.mkdir(parents=True, exist_ok=True)
                rows.to_csv(out, mode="a", index=False,
                            header=not out.exists())
                total_alerts += len(rows)
        print(f"[epoch {epoch}] {p}: {res.n_events} events, "
              f"{len(res.alerts)} alerts, {res.n_new_docs} new docs, "
              f"svi step {res.step}")
    print(f"stream done: {total_events} events, {total_alerts} alerts, "
          f"{len(scorer.pad_shapes)} compiled shapes")
    resil = {**counters.snapshot("stream.batch"),
             **counters.snapshot("faults"),
             **counters.snapshot("salvage")}
    if resil:
        print(f"stream resilience: {resil}")
    return 0
