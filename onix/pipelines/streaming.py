"""Streaming scoring: online-VB LDA over ingest minibatches.

Covers BASELINE.json configs[4] ("streaming online-VB LDA over
oni-ingest minibatches (incremental scoring)") — a capability the
reference does NOT have: oni-lda-c re-fits from scratch once per day
(SURVEY.md §3.1), so a beacon that starts at 09:00 is invisible until
the next day's batch run. onix scores each ingest minibatch the moment
it lands, against a model updated by every batch seen so far.

Streaming-specific design (vs the batch path in pipelines/run.py):

- **Hashed vocabulary.** A batch run fits its vocabulary after seeing
  the whole day; a stream never sees "the whole day". Words hash into a
  fixed number of buckets, so the topic-word parameter lambda [V,K] has
  a static shape forever — the XLA-friendly rendering of an unbounded
  vocabulary. Buckets come from a vectorized splitmix64 over the packed
  int64 `word_key` (`_bucket_of_keys`) — process-stable (unlike
  Python's salted hash) and with no per-row or per-unique string work;
  collisions merge rare words into shared buckets, which for a rarity
  detector is conservative (a colliding rare word can only look MORE
  common, never less).
- **Frozen bin edges.** Quantile edges are fitted on the first batch
  (or a warmup batch) and applied verbatim afterwards; re-fitting per
  batch would silently redefine every word mid-stream.
- **Bounded document table.** IPs get dense doc ids on first sight;
  the per-doc gamma store grows by powers of two so the scoring step
  compiles O(log D) times, not O(batches). With `max_docs` set, the
  least-recently-seen quarter is evicted (and ids compacted) whenever
  the population crosses the bound, so a stream that lives for months
  holds — and checkpoints — O(max_docs) per-doc state, not O(every IP
  ever seen).
- **Static shapes.** Token and doc axes of every minibatch are padded
  to powers of two — a stream of irregular batches reuses a handful of
  compiled programs (asserted in tests).
- **Device-resident word creation (default).** Once the edges freeze,
  each columnar minibatch's binning → packed-key build → splitmix64
  bucketing runs as ONE fused device program (device_words.py
  `*_stream_buckets`): the int64 word key is packed in uint32 limbs and
  hashed with 32-bit limb arithmetic, so buckets are IDENTICAL to the
  host `_bucket_of_keys` (given identical bin indices; f32-vs-f64 edge
  comparisons can differ ~1e-7/event — device_words docstring). The
  per-unique string features (dns/proxy) stay host-side per refresh.
  The tables are rebuilt from the frozen edges per batch only where
  they depend on the batch (caller proto order, the batch's unique
  string values) — O(uniques), not O(events).
- **Deduped weighted E-step.** The minibatch fed to SVI is the UNIQUE
  (doc, bucket) pairs with their counts as token weights
  (`make_minibatch(weights=...)`): every E-step/λ-step contribution
  multiplies by the weight, so the math is exactly the repeated-token
  update at a fraction of the [T,K] passes (telemetry is Zipf — unique
  pairs run 4-5x below the token count). Scoring broadcasts the
  unique-pair scores back through the inverse index, so per-event
  scores and alerts are unchanged in meaning.
- **Warm/cold compacted E-step (r10).** The local E-step runs a short
  fixed-trip warm pass over the full padded block (returning docs —
  the stream's common case — converge inside it thanks to the gamma
  warm start), then COMPACTS the unconverged remainder's tokens into
  the smallest pow2 bucket that fits and runs the extended
  per-document while_loop only there (lda_svi._run_e_step): extended
  iterations stop charging every token for the slowest doc.
- **Minibatch supersteps (r10).** `process_many` with
  pipeline.stream_superstep = S chains S batches' E-step +
  natural-gradient λ-step + incremental scoring inside ONE jitted
  program (lda_svi.svi_superstep), warm starts flowing batch-to-batch
  through a device-resident union gamma store and the scores block
  fetched once per superstep — ~1 dispatch sync per S batches where
  the per-batch path pays ~2 per batch (plus words), the exact
  dispatch-amortization move of the r7 Gibbs fit supersteps.
- **Depth-k host pipeline (r10).** ColumnPrefetcher keeps up to k
  future batches' file decode + frame→columns conversion in flight on
  worker threads or a process pool (measured auto-pick; bounded,
  in-order, backpressured), so the ~30% host slice of the batch wall
  (docs/PERF.md r6) rides under the device step.
- **Capped shape lattice (r10).** `_pick_pad` bounds the compiled
  (pad_to, pad_docs) set: past `pipeline.stream_max_shapes`,
  adversarial batch-size streams re-pad into covering shapes instead
  of silently recompiling per batch; compiles and re-pads are counted
  (shape_stats + stream.shape_* obs counters).
- **Escape hatch.** ONIX_HOST_WORDS=1 pins the host reference path
  (word builders + host hash + undeduped E-step) — the cross-check arm
  measurements compare against. The host path also catches everything
  the device path declines: the first batch (edges still fitting),
  string/IPv6 doc keys, non-power-of-two bucket counts, and frames the
  columnar converter rejects.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import os
import pathlib
import time

import numpy as np
import pandas as pd

from onix.config import OnixConfig
from onix.models.lda_svi import SVILda, SVIState, make_minibatch, phi_estimate
from onix.models.scoring import score_all
from onix.pipelines.words import WORD_FNS
from onix.utils import resilience
from onix.utils.obs import counters


def _next_pow2(n: int, floor: int = 256) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p


def _bucket_of_keys(word_keys: np.ndarray, salt: int,
                    n_buckets: int) -> np.ndarray:
    """Packed int64 word keys → stable bucket ids, fully vectorized.

    The r03 scorer rendered every word to its display STRING and
    blake2b-hashed the unique strings per batch — measured as a top
    host cost of the 58k ev/s streaming wall (VERDICT r03 weak #6).
    Every word path (string or columnar) already carries the packed
    integer `word_key`, and rendering is a bijection given frozen
    edges, so hashing the key is the same identity at none of the
    string cost. splitmix64 finalizer: deterministic across processes
    (unlike Python's salted hash), full-avalanche, one vector pass."""
    x = word_keys.astype(np.uint64) ^ np.uint64(salt)
    x = (x + np.uint64(0x9E3779B97F4A7C15)) & np.uint64(0xFFFFFFFFFFFFFFFF)
    x ^= x >> np.uint64(30)
    x = (x * np.uint64(0xBF58476D1CE4E5B9)) & np.uint64(0xFFFFFFFFFFFFFFFF)
    x ^= x >> np.uint64(27)
    x = (x * np.uint64(0x94D049BB133111EB)) & np.uint64(0xFFFFFFFFFFFFFFFF)
    x ^= x >> np.uint64(31)
    return (x % np.uint64(n_buckets)).astype(np.int32)


def _datatype_salt(datatype: str) -> int:
    """Stable per-datatype hash salt (keys of different datatypes must
    not systematically collide into the same buckets)."""
    return int.from_bytes(
        hashlib.blake2b(datatype.encode(), digest_size=8).digest(),
        "little")


class DocTable:
    """IP string → dense doc id, first-seen order.

    Growth is bounded by the owner (StreamingScorer evicts idle docs
    via `compact`); `load` restores a saved key list in one bulk pass —
    the round-2 restore replayed checkpointed IPs one at a time, which
    at the reference's ~10⁶-IP scale took minutes (VERDICT r2 weak #8).
    """

    def __init__(self):
        self._index: dict[str, int] = {}
        self.keys: list[str] = []

    @property
    def n_docs(self) -> int:
        return len(self.keys)

    def ids(self, ips: np.ndarray) -> np.ndarray:
        uniq, inv = np.unique(np.asarray(ips, dtype=object), return_inverse=True)
        out = np.empty(len(uniq), np.int32)
        for i, ip in enumerate(uniq):
            idx = self._index.get(ip)
            if idx is None:
                idx = len(self.keys)
                self._index[ip] = idx
                self.keys.append(ip)
            out[i] = idx
        return out[inv]

    def load(self, keys) -> None:
        """Bulk-replace the table (vectorized restore path)."""
        self.keys = [str(k) for k in keys]
        self._index = {k: i for i, k in enumerate(self.keys)}

    def compact(self, keep_mask: np.ndarray) -> np.ndarray:
        """Drop docs where ~keep_mask; survivors keep first-seen order
        with new dense ids. Returns the OLD ids of the survivors (the
        gather index for any id-parallel array, e.g. gamma rows)."""
        keep_idx = np.flatnonzero(keep_mask)
        self.keys = [self.keys[i] for i in keep_idx]
        self._index = {k: i for i, k in enumerate(self.keys)}
        return keep_idx


class U32DocTable:
    """uint32 IP → dense doc id, first-seen order — the integer twin of
    DocTable for the columnar streaming path (no per-row IP strings
    anywhere in the hot loop). `keys` is a uint32 array; `as_strings()`
    renders dotted-quads for the one-way conversion to string mode when
    a stream hits a non-columnar batch mid-flight (canonical v4 strings
    are the same doc identities, so the switch is lossless)."""

    def __init__(self):
        self._index: dict[int, int] = {}
        self.keys = np.zeros(0, np.uint32)

    @property
    def n_docs(self) -> int:
        return len(self.keys)

    def ids(self, ips_u32: np.ndarray) -> np.ndarray:
        uniq, inv = np.unique(np.asarray(ips_u32, np.uint32),
                              return_inverse=True)
        out = np.empty(len(uniq), np.int32)
        fresh = []
        n = len(self.keys)
        for i, ip in enumerate(uniq.tolist()):
            idx = self._index.get(ip)
            if idx is None:
                idx = n + len(fresh)
                self._index[ip] = idx
                fresh.append(ip)
            out[i] = idx
        if fresh:
            self.keys = np.concatenate(
                [self.keys, np.asarray(fresh, np.uint32)])
        return out[inv]

    def load(self, keys) -> None:
        self.keys = np.asarray(keys, np.uint32)
        self._index = {int(k): i for i, k in enumerate(self.keys.tolist())}

    def compact(self, keep_mask: np.ndarray) -> np.ndarray:
        keep_idx = np.flatnonzero(keep_mask)
        self.keys = self.keys[keep_idx]
        self._index = {int(k): i for i, k in enumerate(self.keys.tolist())}
        return keep_idx

    def as_strings(self) -> list[str]:
        from onix.pipelines.words import u32_to_ips
        return u32_to_ips(self.keys).tolist()


@dataclasses.dataclass
class _Prep:
    """Host-prepared minibatch (output of StreamingScorer._prep_batch):
    everything the device step and the emit tail need, shared by the
    per-batch and superstep paths."""

    table: pd.DataFrame
    n_events: int
    event_idx: np.ndarray
    dev_flow: bool              # device flow [src|dst] token layout
    did_b: np.ndarray           # batch doc ids (deduped rows)
    wid_b: np.ndarray
    weights: np.ndarray | None
    inv: np.ndarray | None      # pair -> token inverse (None = undeduped)
    t: int                      # raw token count
    t_rows: int                 # deduped row count fed to the model
    n_batch_docs: int
    docs_before: int
    n_docs_after: int
    # Noise-filter key streams (r13, onix/feedback/): the per-token
    # bucket ids and the per-EVENT packed pair key — (sip, dip) for
    # flow, (client, bucket) for dns/proxy — None on the string-keyed
    # doc path (no stable 32-bit identities to pack).
    wid_tok: np.ndarray | None = None
    ev_pair: np.ndarray | None = None


@dataclasses.dataclass
class BatchResult:
    """Incremental scoring output for one minibatch."""

    scores: np.ndarray        # float64 [n_events] per-event score
    alerts: pd.DataFrame      # events with score < tol, ascending, enriched
    n_events: int
    n_new_docs: int
    step: int                 # global SVI step after this batch


class StreamingScorer:
    """Online-VB LDA fed by ingest minibatches, scoring as it goes.

    Usage: one instance per datatype stream; call `process(table)` for
    each decoded minibatch (a file, a Kafka-equivalent queue drain, a
    store partition slice). Returns per-event scores plus the alert rows
    under `tol`."""

    def __init__(self, cfg: OnixConfig, datatype: str,
                 n_buckets: int = 1 << 15,
                 checkpoint_dir: str | None = None, resume: bool = True,
                 max_docs: int | None = None):
        cfg.validate()
        if n_buckets < 2:
            raise ValueError("n_buckets must be >= 2")
        self.cfg = cfg
        self.datatype = datatype
        self.n_buckets = int(n_buckets)
        self._salt = _datatype_salt(datatype)
        # Integer-keyed doc table while every batch goes columnar; a
        # one-way switch to the string table happens on the first batch
        # the columnar converter rejects (e.g. IPv6 strings).
        self.docs: U32DocTable | DocTable = U32DocTable()
        self.word_fn = WORD_FNS[datatype]
        self.edges: dict | None = None
        # Effective model config: svi_warm_iters=-1 resolves to the
        # streaming auto default (4 warm trips, then the compacted
        # active-set extension — lda_svi._run_e_step). The EFFECTIVE
        # value feeds the SVILda jits and the checkpoint fingerprint.
        lda = cfg.lda
        if lda.svi_warm_iters < 0:
            lda = dataclasses.replace(lda, svi_warm_iters=4)
        self._lda_eff = lda
        self.model = SVILda(lda, n_buckets, corpus_docs=1)
        self.state: SVIState = self.model.init()
        # Superstep size (pipeline.stream_superstep): S minibatch
        # updates chained in one dispatch via process_many; <=1 keeps
        # the per-batch path.
        self.superstep = max(1, int(cfg.pipeline.stream_superstep))
        self.max_shapes = max(1, int(cfg.pipeline.stream_max_shapes))
        k = cfg.lda.n_topics
        self._gamma = np.full((_next_pow2(1), k), cfg.lda.alpha, np.float32)
        # Eviction bound on per-doc state: a long-lived stream sees an
        # unbounded IP population, so gamma/doc-table growth must have a
        # ceiling. When n_docs crosses `max_docs`, the least-recently-
        # seen quarter is dropped (an evicted IP that returns restarts
        # from the prior — for a rarity detector that direction is
        # conservative: a fresh doc's uniform theta cannot make its
        # events look rarer than history would).
        self.max_docs = max_docs
        self._last_seen = np.zeros(self._gamma.shape[0], np.int64)
        self.pad_shapes: set[tuple[int, int]] = set()   # compile accounting
        # Superstep program shapes (S, pad_to, pad_docs) — its own
        # lattice dimension next to pad_shapes.
        self.superstep_shapes: set[tuple[int, int, int]] = set()
        # Cumulative per-stage walls (seconds) — the r03 streaming rate
        # was 300x under the batch scan with the host path unprofiled
        # (VERDICT r03 weak #6); every artifact now carries the split.
        # prefetch_overlap/prefetch_wait account the one-deep conversion
        # prefetch (ColumnPrefetcher): overlap = frame→columns seconds
        # that ran hidden under the previous batch's step, wait = the
        # residual the consumer still blocked on.
        self.stage_walls = {"words": 0.0, "ids": 0.0, "minibatch": 0.0,
                            "svi_update": 0.0, "score": 0.0, "emit": 0.0,
                            "prefetch_overlap": 0.0, "prefetch_wait": 0.0}
        # Which word path each batch rode (device fused vs host
        # reference) — artifacts report it next to the stage walls.
        self.words_mode_batches = {"device": 0, "host": 0}
        # Device dispatch syncs per program family — the number the
        # superstep collapses (one svi_update+score dispatch per S
        # batches instead of two per batch), tracked so artifacts and
        # bench.py report it instead of inferring it.
        self.dispatches = {"words": 0, "svi_update": 0, "score": 0,
                           "superstep": 0}
        # Shape-lattice accounting (_pick_pad): every NEW (pad_to,
        # pad_docs) pair is a recompile of the svi/score programs;
        # "repadded" counts batches folded into a covering shape once
        # the lattice cap is reached.
        self.shape_stats = {"compiled": 0, "repadded": 0}
        # Deduped rows actually fed to the model (the roofline's item
        # count) and raw events, accumulated per batch.
        self.pair_rows = 0
        self.events_seen = 0
        # Prefetch pipeline accounting, filled by ColumnPrefetcher:
        # depth/mode, queue occupancy at each handoff, worker busy
        # seconds, and the thread-vs-process calibration that picked
        # the mode.
        self.prefetch_stats: dict = {}
        # r13 analyst feedback: the compiled noise filter (None until
        # the first apply_feedback; persists through checkpoints) and
        # the application tally the replay harness reports.
        self.noise_filter = None
        self.feedback_stats = {"applied": 0, "suppress_keys": 0,
                               "boost_keys": 0, "online_steps": 0}
        self._batch_no = 0
        self.checkpoint_dir = (pathlib.Path(checkpoint_dir)
                               if checkpoint_dir else None)
        if self.checkpoint_dir is not None and resume:
            self._restore_latest()

    # -- checkpoint / resume (SURVEY.md §5.3-5.4) -------------------------
    #
    # A preempted stream must not lose the model: round 1 held SVIState,
    # hashed-vocab params, DocTable, gamma, and the frozen edges purely
    # in memory — the exact failure checkpointing exists to prevent.
    # Everything needed to continue (and to score identically) persists
    # every `lda.checkpoint_every` batches.

    def _fingerprint(self) -> str:
        from onix import checkpoint as ckpt

        # checkpoint.fingerprint's sampling fields are Gibbs-oriented;
        # the SVI schedule knobs change what this engine computes, so a
        # checkpoint under a different schedule must not be adopted.
        lda = self._lda_eff
        # layout=5: the local update gained the SCVB0 arm
        # (lda.stream_estep joins the schedule identity — a lambda
        # trained under the collapsed estimator is a different model
        # and must not be adopted by the svi arm, or vice versa).
        # layout=4 added the warm/cold compacted split (svi_warm_iters);
        # layout=3 hashed the packed word_key (splitmix64), not the
        # rendered string.
        return ckpt.fingerprint(
            lda, 0, self.n_buckets, 0,
            extra={"stream_datatype": self.datatype,
                   "n_buckets": self.n_buckets,
                   # meanchange joined when the E-step gained the
                   # convergence stop; warm_iters (EFFECTIVE value,
                   # after the -1 auto resolve) when it gained the
                   # warm/cold split; estep_form when the SCVB0 arm
                   # landed.
                   "svi": [lda.svi_tau0, lda.svi_kappa,
                           lda.svi_local_iters, lda.svi_meanchange_tol,
                           lda.svi_warm_iters, lda.stream_estep],
                   "layout": 5})

    def save_checkpoint(self) -> None:
        from onix import checkpoint as ckpt
        if self.checkpoint_dir is None:
            return
        edges = None
        if self.edges is not None:
            edges = {k: (v if isinstance(v, list) else np.asarray(v).tolist())
                     for k, v in self.edges.items()}
        n = self.docs.n_docs
        # Per-doc state goes in the npz as COLUMNS trimmed to n_docs —
        # round 2 serialized every IP string into the JSON meta (tens of
        # MB at 10⁶ docs) and saved gamma at padded capacity. The doc
        # key column matches the live table mode: a raw uint32 array on
        # the columnar path (4 B/doc), utf-8 strings otherwise.
        u32_mode = isinstance(self.docs, U32DocTable)
        doc_keys = (self.docs.keys if u32_mode else np.char.encode(
            np.asarray(self.docs.keys, dtype=str), "utf-8"))
        # The noise filter rides the checkpoint (empty arrays when no
        # feedback was ever applied): a resumed stream must keep
        # suppressing what the analyst already dismissed. Keys are raw
        # u32-pair/bucket identities, so they survive doc-table
        # eviction/compaction unchanged.
        f = self.noise_filter
        e64 = np.empty(0, np.uint64)
        ckpt.save(
            self.checkpoint_dir / self._fingerprint(), self._batch_no,
            {"lam": np.asarray(self.state.lam),
             "step": np.asarray(self.state.step),
             "gamma": self._gamma[:n],
             "doc_keys": doc_keys,
             "last_seen": self._last_seen[:n],
             "fb_word_sup": f.word_suppress if f else e64,
             "fb_word_boost": f.word_boost if f else e64,
             "fb_pair_sup": f.pair_suppress if f else e64,
             "fb_pair_boost": f.pair_boost if f else e64},
            {"fingerprint": self._fingerprint(), "engine": "streaming",
             "datatype": self.datatype, "doc_key_mode":
                 "u32" if u32_mode else "str",
             "fb_boost_scale": (f.boost_scale if f
                                else self.cfg.feedback.boost_scale),
             "edges": edges})

    def _restore_latest(self) -> bool:
        import jax.numpy as jnp

        from onix import checkpoint as ckpt
        saved = ckpt.load_latest(self.checkpoint_dir / self._fingerprint())
        if saved is None or saved.meta.get("fingerprint") != self._fingerprint():
            return False
        self.state = SVIState(lam=jnp.asarray(saved.arrays["lam"]),
                              step=jnp.asarray(saved.arrays["step"]))
        if saved.meta.get("doc_key_mode", "str") == "u32":
            self.docs = U32DocTable()
            self.docs.load(saved.arrays["doc_keys"])
        else:
            self.docs = DocTable()
            self.docs.load(np.char.decode(saved.arrays["doc_keys"],
                                          "utf-8"))
        n = self.docs.n_docs
        cap = _next_pow2(max(n, 1))
        k = saved.arrays["gamma"].shape[1]
        self._gamma = np.full((cap, k), self.cfg.lda.alpha, np.float32)
        self._gamma[:n] = saved.arrays["gamma"]
        self._last_seen = np.zeros(cap, np.int64)
        self._last_seen[:n] = saved.arrays["last_seen"]
        edges = saved.meta.get("edges")
        self.edges = ({k: (v if isinstance(v, list) and v
                           and isinstance(v[0], str) else np.asarray(v))
                       for k, v in edges.items()}
                      if edges is not None else None)
        # Noise filter (absent in pre-r13 checkpoints: stays None).
        if "fb_word_sup" in saved.arrays:
            from onix.feedback.filter import HostFilter
            f = HostFilter(
                np.asarray(saved.arrays["fb_word_sup"], np.uint64),
                np.asarray(saved.arrays["fb_word_boost"], np.uint64),
                np.asarray(saved.arrays["fb_pair_sup"], np.uint64),
                np.asarray(saved.arrays["fb_pair_boost"], np.uint64),
                float(saved.meta.get("fb_boost_scale",
                                     self.cfg.feedback.boost_scale)))
            self.noise_filter = None if f.empty_filter else f
        self._batch_no = saved.sweep
        return True

    # -- internals --------------------------------------------------------

    def _grow(self, n_docs: int) -> None:
        cap = self._gamma.shape[0]
        if n_docs <= cap:
            return
        new_cap = _next_pow2(n_docs, floor=cap)
        grown = np.full((new_cap, self._gamma.shape[1]),
                        self.cfg.lda.alpha, np.float32)
        grown[:cap] = self._gamma
        self._gamma = grown
        seen = np.zeros(new_cap, np.int64)
        seen[:cap] = self._last_seen
        self._last_seen = seen

    def _maybe_evict(self) -> int:
        """Keep the doc population under `max_docs`: when crossed, drop
        the least-recently-seen quarter and compact ids/gamma/last_seen
        so the stream's per-doc state (and its checkpoints) stay
        bounded no matter how many distinct IPs it ever sees."""
        if self.max_docs is None or self.docs.n_docs <= self.max_docs:
            return 0
        n = self.docs.n_docs
        target = max(1, int(self.max_docs * 0.75))
        # Survivors = the `target` most recently seen (ties broken by
        # doc id: older docs go first, matching LRU intent).
        order = np.lexsort((np.arange(n), -self._last_seen[:n]))
        keep = np.zeros(n, bool)
        keep[order[:target]] = True
        old_ids = self.docs.compact(keep)
        n_new = len(old_ids)
        cap = _next_pow2(max(n_new, 1))
        gamma = np.full((cap, self._gamma.shape[1]),
                        self.cfg.lda.alpha, np.float32)
        gamma[:n_new] = self._gamma[old_ids]
        seen = np.zeros(cap, np.int64)
        seen[:n_new] = self._last_seen[old_ids]
        self._gamma, self._last_seen = gamma, seen
        return n - n_new

    def _pick_pad(self, t_rows: int, n_docs: int) -> tuple[int, int]:
        """Pad shape for one minibatch, with a CAPPED shape lattice.

        The naive pow2 pair (pad_to, pad_docs) grows the compiled-
        program set unboundedly on adversarial streams — every new
        pair is a silent recompile (5-30 s each through the TPU
        tunnel). Min-bucket floors (256 tokens / 64 docs) absorb small
        batches; once `max_shapes` distinct pairs have compiled, a new
        batch re-pads into the smallest EXISTING covering shape, and
        if nothing covers it the lattice grows one ceiling shape that
        covers everything seen so far (so post-cap growth is O(log
        max_batch), not O(batches)). Every new pair increments
        shape_stats["compiled"] + the stream.shape_compiles counter;
        re-pads count too, so run summaries show both."""
        need = (_next_pow2(t_rows), _next_pow2(n_docs, floor=64))
        if need in self.pad_shapes:
            return need
        if len(self.pad_shapes) >= self.max_shapes:
            covering = [s for s in self.pad_shapes
                        if s[0] >= need[0] and s[1] >= need[1]]
            if covering:
                self.shape_stats["repadded"] += 1
                counters.inc("stream.shape_repads")
                return min(covering)
            # Nothing covers this batch: escalate to one ceiling shape
            # (covers every existing shape too, so the lattice can only
            # grow again if a batch exceeds THIS).
            need = (max(need[0], max(s[0] for s in self.pad_shapes)),
                    max(need[1], max(s[1] for s in self.pad_shapes)))
        self.pad_shapes.add(need)
        self.shape_stats["compiled"] += 1
        counters.inc("stream.shape_compiles")
        return need

    # -- the streaming step -----------------------------------------------

    def convert_columns(self, table: pd.DataFrame) -> dict | None:
        """frame → numeric columns, or None for frames the converter
        rejects (malformed columns — those ride the string word path).

        Pure host work on an immutable frame with NO scorer state read
        or written (the columnar converters don't need the bin edges),
        so it is safe to run on a prefetch thread (or a process-pool
        worker — `_convert_frame` is module-level for exactly that)
        while the previous batch's device step occupies the main
        thread; `process(table, cols=...)` consumes the result without
        re-converting."""
        return _convert_frame(self.datatype, table)

    def _words(self, table: pd.DataFrame, cols: dict | None = None):
        """One minibatch → WordTable, columnar-first.

        The frame converters do the per-UNIQUE-value string work and the
        *_words_from_arrays builders everything per-row in NumPy — the
        same machinery as the batch scale runner. IPv6/non-canonical
        addresses ride the tagged-u64 dictionary (words.IP_TAG), which
        has no uint32 doc keys — such batches flip the doc table
        one-way to string keys (same raw-string identities). A frame
        the converter rejects outright (malformed columns) falls back
        to the string word path; word identity is unaffected either
        way (both paths emit the same packed word_key)."""
        from onix.pipelines import columnar

        if cols is None:
            cols = self.convert_columns(table)
        if cols is None:
            return self.word_fn(table, edges=self.edges)
        return columnar.words_from_cols(self.datatype, cols,
                                        edges=self.edges)

    def _device_words(self, table: pd.DataFrame,
                      cols: dict | None = None):
        """Fused device word path for one minibatch: columnar convert
        (host, per-unique string work — prefetchable, see
        convert_columns) → ONE jitted program for binning + key packing
        + splitmix64 bucketing. Returns (bucket ids [T], ip_u32 [T],
        event_idx [T]) in the host token layout, or None when the batch
        must ride the host path (docstring list)."""
        import jax.numpy as jnp

        from onix.pipelines import device_words as dw

        if cols is None:
            cols = self.convert_columns(table)
        if cols is None:
            return None
        if "ip_table" in cols:      # IPv6/non-canonical: string doc keys
            return None
        n = len(table)
        pad = _next_pow2(n)

        def _cols(names, dtypes):
            # Pow2-pad the per-event columns so the jitted bucket
            # program compiles once per SIZE CLASS, not once per batch
            # length (the module's static-shape contract; through the
            # TPU tunnel a retrace costs 5-30 s). Zero padding is safe:
            # every program is elementwise and row 0 of each gathered
            # table exists; the pad rows are sliced off below.
            return [jnp.asarray(np.pad(np.asarray(cols[c], d),
                                       (0, pad - n)))
                    for c, d in zip(names, dtypes)]

        if self.datatype == "flow":
            t = dw.build_flow_stream_tables(
                self.edges, list(cols["proto_classes"]))
            wid_e = np.asarray(dw.flow_stream_buckets(
                t, *_cols(("sport", "dport", "proto_id", "hour", "ibyt",
                           "ipkt"),
                          (np.int32, np.int32, np.int32, np.float32,
                           np.float32, np.float32)),
                salt=self._salt, n_buckets=self.n_buckets))[:n]
            ev = np.arange(n, dtype=np.int64)
            return (np.concatenate([wid_e, wid_e]),
                    np.concatenate([cols["sip_u32"], cols["dip_u32"]]),
                    np.concatenate([ev, ev]))
        if self.datatype == "dns":
            t = dw.build_dns_stream_tables(self.edges, cols["qnames"])
            wid = np.asarray(dw.dns_stream_buckets(
                t, *_cols(("qname_codes", "qtype", "rcode", "frame_len",
                           "hour"),
                          (np.int32, np.int32, np.int32, np.float32,
                           np.float32)),
                salt=self._salt, n_buckets=self.n_buckets))[:n]
        else:
            t = dw.build_proxy_stream_tables(
                self.edges, cols["uris"], cols["hosts"], cols["agents"])
            wid = np.asarray(dw.proxy_stream_buckets(
                t, *_cols(("uri_codes", "host_codes", "ua_codes",
                           "respcode", "hour"),
                          (np.int32, np.int32, np.int32, np.int32,
                           np.float32)),
                salt=self._salt, n_buckets=self.n_buckets))[:n]
        return (wid, np.asarray(cols["client_u32"], np.uint32),
                np.arange(n, dtype=np.int64))

    def _device_eligible(self) -> bool:
        from onix.pipelines.device_words import host_words_forced

        return (self.edges is not None                   # frozen
                and isinstance(self.docs, U32DocTable)
                and self.n_buckets & (self.n_buckets - 1) == 0
                and not host_words_forced())

    def _prep_batch(self, table: pd.DataFrame, cols: dict | None):
        """Host half of one minibatch — word-create, doc ids, deduped
        pair build — shared by process() and process_many() so the
        per-batch and superstep arms cannot drift. Mutates scorer
        state in stream order (edge freeze, doc-table growth)."""
        t_stage = time.perf_counter
        t0 = t_stage()
        dev = (self._device_words(table, cols)
               if self._device_eligible() else None)
        if dev is None:
            words = self._words(table, cols)
            if self.edges is None:
                self.edges = words.edges   # frozen from the first batch on
        else:
            self.dispatches["words"] += 1
        self.words_mode_batches["host" if dev is None else "device"] += 1
        self.stage_walls["words"] += t_stage() - t0

        t0 = t_stage()
        docs_before = self.docs.n_docs
        if dev is not None:
            wid, ip_u32, event_idx = dev
            did = self.docs.ids(ip_u32)
        else:
            # Buckets from the packed integer keys — no per-row (or even
            # per-unique) string rendering in the hot loop.
            wid = _bucket_of_keys(words.word_key, self._salt,
                                  self.n_buckets)
            event_idx = words.event_idx
            if words.ip_u32 is not None and isinstance(self.docs,
                                                       U32DocTable):
                did = self.docs.ids(words.ip_u32)
            else:
                if isinstance(self.docs, U32DocTable):
                    # First non-columnar batch: convert to string keys
                    # once (canonical v4 — identical doc identities).
                    str_table = DocTable()
                    str_table.load(self.docs.as_strings())
                    self.docs = str_table
                did = self.docs.ids(words.ip)
        self._grow(self.docs.n_docs)
        self.stage_walls["ids"] += t_stage() - t0

        t0 = t_stage()
        t = len(wid)
        inv = None
        from onix.pipelines.device_words import host_words_forced
        if not host_words_forced():
            # Unique (doc, bucket) pairs with counts: the E-step and
            # scoring run over U << T weighted rows; `inv` broadcasts
            # pair scores back to tokens (MiniBatch mask semantics).
            # Independent of the word path — a host-words batch (edges
            # still fitting, IPv6, rejected frame) still dedups.
            pair = did.astype(np.int64) * self.n_buckets + wid
            uniq, inv, cnt = np.unique(pair, return_inverse=True,
                                       return_counts=True)
            did_b = (uniq // self.n_buckets).astype(np.int32)
            wid_b = (uniq % self.n_buckets).astype(np.int32)
            weights = cnt.astype(np.float32)
            t_rows = len(uniq)
        else:
            did_b, wid_b, weights, t_rows = did, wid, None, t
        n_batch_docs = len(np.unique(did_b))
        self.pair_rows += t_rows
        self.events_seen += len(table)
        # Noise-filter event keys (r13): the packed pair identity per
        # EVENT, from the raw u32 identities (stable across doc-table
        # eviction/compaction — doc ids are not). Flow tokens are the
        # [src|dst] halves of the same events in order on BOTH word
        # paths (words.flow_words_from_arrays / _device_words), so the
        # pair is one slice-and-pack; dns/proxy pairs are (client,
        # bucket). String-keyed doc tables carry no u32s — pair
        # filtering is off there, word-bucket filtering still applies.
        ips = ip_u32 if dev is not None else words.ip_u32
        n = len(table)
        ev_pair = None
        if ips is not None:
            from onix.feedback.filter import pack_pair
            if self.datatype == "flow" and len(ips) == 2 * n:
                ev_pair = pack_pair(ips[:n], ips[n:])
            elif self.datatype != "flow" and len(ips) == n:
                # One token per event, but not necessarily in event
                # order — scatter through event_idx.
                ev_pair = np.zeros(n, np.uint64)
                ev_pair[event_idx] = pack_pair(ips,
                                               wid.astype(np.uint32))
        self.stage_walls["minibatch"] += t_stage() - t0
        return _Prep(table=table, n_events=len(table),
                     event_idx=event_idx,
                     dev_flow=dev is not None and self.datatype == "flow",
                     did_b=did_b, wid_b=wid_b, weights=weights, inv=inv,
                     t=t, t_rows=t_rows, n_batch_docs=n_batch_docs,
                     docs_before=docs_before,
                     n_docs_after=self.docs.n_docs,
                     wid_tok=wid, ev_pair=ev_pair)

    def _emit(self, p: "_Prep", tok_scores: np.ndarray,
              evict: bool = True) -> BatchResult:
        """Per-event reduce + alert rows + batch bookkeeping for one
        prepared minibatch (shared tail of both paths).

        The noise filter (r13) applies HERE, on the hot path's winner
        selection: word-bucket adjustments on the token scores before
        the event min-reduce, pair adjustments on the event scores
        before the tol screen — the same boost-then-suppress-then-tol
        order as the fused device scans (feedback/rescore.py), at the
        point where scores are already host-side for selection. An
        absent or EMPTY filter skips every adjustment outright, so the
        no-feedback stream is bit-identical to pre-filter behavior."""
        t0 = time.perf_counter()
        n_events = p.n_events
        # The config gate (feedback.filter_enabled) applies at INSTALL
        # time (apply_feedback's `immediate` default) — an explicitly
        # requested immediate=True install must also be APPLIED, so
        # application is gated only on a non-empty installed filter.
        f = self.noise_filter
        if f is not None and f.empty_filter:
            f = None
        tol = self.cfg.pipeline.tol
        ev_scores = hit = None
        # r15 one-kernel serving tail (flow device layout only — the
        # hot path): word adjust + min-reduce + pair adjust + tol
        # screen + bottom-M in ONE fused pallas_serve program behind
        # the serve gate (serving.serve_form / ONIX_SERVE_FORM; "auto"
        # keeps the host tail until a measured crossover lands). The
        # string-keyed fallback (no u32 pair identities under a
        # non-empty filter) stays on the host tail, which can apply
        # word-only filtering.
        if p.dev_flow and p.wid_tok is not None \
                and (f is None or p.ev_pair is not None):
            from onix.models.pallas_serve import select_serve_form
            if select_serve_form(self.cfg.serving.serve_form,
                                 n_events) == "fused":
                ev_scores, hit = self._fused_tail(p, tok_scores, f, tol)
        if hit is None:
            if f is not None and p.wid_tok is not None:
                tok_scores = f.apply_word(tok_scores,
                                          p.wid_tok.astype(np.uint64))
            if p.dev_flow:
                # Device flow layout is [src|dst] tokens of the same
                # events in order: the event min is one elementwise
                # minimum, not an unbuffered scatter.
                ev_scores = np.minimum(
                    tok_scores[:n_events],
                    tok_scores[n_events:]).astype(np.float64)
            else:
                ev_scores = np.full(n_events, np.inf, np.float64)
                np.minimum.at(ev_scores, p.event_idx, tok_scores)
            if f is not None and p.ev_pair is not None:
                before = ev_scores
                ev_scores = f.apply_pair(ev_scores, p.ev_pair)
                if ev_scores is not before:
                    counters.inc("feedback.rescored_events",
                                 int(np.sum(~np.isfinite(ev_scores)
                                            & np.isfinite(before))))

            hit = np.flatnonzero(ev_scores < tol)
            hit = hit[np.argsort(ev_scores[hit], kind="stable")]
            hit = hit[: self.cfg.pipeline.max_results]
        alerts = p.table.iloc[hit].copy()
        alerts.insert(0, "score", ev_scores[hit])
        alerts.insert(1, "event_idx", hit)

        self._batch_no += 1
        if evict:
            self._maybe_evict()
        n_after = self.docs.n_docs if evict else p.n_docs_after
        self.stage_walls["emit"] += time.perf_counter() - t0
        return BatchResult(scores=ev_scores, alerts=alerts,
                           n_events=n_events,
                           n_new_docs=n_after - p.docs_before,
                           step=int(self.state.step))

    def _fused_tail(self, p: "_Prep", tok_scores, f, tol):
        """The one-kernel winner-selection tail (pallas_serve.
        fused_stream_tail): returns (ev_scores float64, hit indices) in
        the host tail's exact contract — winners ascending by (score,
        event index), capped at max_results; scores are the fully
        filter-adjusted stream. The kernel computes in f32 (the device
        dtype): identical to the float64 host tail whenever boost_scale
        is dyadic (the 0.25 default — the multiply is then exact in
        both widths) and no score falls inside the one-ulp gap between
        tol and f32(tol); the tier-1 parity test pins both."""
        from onix.feedback.filter import split_key
        from onix.models.pallas_serve import fused_stream_tail
        n = p.n_events
        if f is not None:
            # HostFilter is immutable and REPLACED (never mutated) on
            # every change, so an identity check keeps the device
            # rendering cached across batches instead of re-padding +
            # re-uploading four key families per batch.
            cached = getattr(self, "_fused_tail_tables", None)
            if cached is None or cached[0] is not f:
                cached = (f, f.tables())
                self._fused_tail_tables = cached
            tabs = cached[1]
            ph_, pl_ = split_key(p.ev_pair)
        else:
            tabs = ph_ = pl_ = None
        topk, ev_dev = fused_stream_tail(
            np.asarray(tok_scores[:n], np.float32),
            np.asarray(tok_scores[n:], np.float32),
            None if f is None else p.wid_tok[:n].astype(np.uint32),
            None if f is None else p.wid_tok[n:].astype(np.uint32),
            ph_, pl_, tabs, tol=float(tol),
            max_results=self.cfg.pipeline.max_results)
        ev_scores = np.asarray(ev_dev).astype(np.float64)
        hit = np.asarray(topk.indices)
        hit = hit[hit >= 0]
        counters.inc("serve.fused_tail")
        if f is not None:
            # The SAME metric the host tail counts (events newly +inf
            # at the PAIR stage): pair-suppress members whose score was
            # still finite after the word stage — token scores are
            # finite, so only both-tokens-word-suppressed events enter
            # the pair stage already at +inf. Host-side membership over
            # the tiny unpadded tables, so flipping the arm never zeroes
            # the monitoring counter.
            pair_sup = f.member(p.ev_pair, f.pair_suppress)
            if pair_sup.any():
                wkeys = p.wid_tok.astype(np.uint64)
                word_sup = f.member(wkeys[:n], f.word_suppress) \
                    & f.member(wkeys[n:], f.word_suppress)
                counters.inc("feedback.rescored_events",
                             int(np.sum(pair_sup & ~word_sup)))
        return ev_scores, hit

    # -- analyst feedback (r13, onix/feedback/) ---------------------------
    #
    # The loop the OA layer exists for: verdicts on alert rows flow
    # back into (a) the noise filter — the dismissed identity vanishes
    # from the NEXT batch's winner set — and (b) an incremental
    # feedback-weighted λ update through the same svi_step machinery
    # the stream already runs, so the model itself stops scoring the
    # dismissed traffic suspicious without a cold refit.

    def apply_feedback(self, rows: pd.DataFrame, labels,
                       immediate: bool | None = None,
                       online: bool | None = None) -> dict:
        """Apply analyst verdicts on raw telemetry rows (typically
        alert rows from an earlier BatchResult). `labels` follows the
        reference severity scale per row: 1/2 confirmed threat (boost),
        3 benign (suppress/dismiss).

        Identities are re-derived through the SAME frozen-edge word
        path the stream scores with (word buckets from the packed key,
        u32 doc identities), so the filter keys match future batches
        exactly. `immediate`/`online` override the config gates
        (feedback.filter_enabled / dismiss_weight > 0) — the replay
        harness uses them to isolate the two timescales."""
        from onix.feedback.filter import (BENIGN_LABEL, HostFilter,
                                          pack_pair)

        if self.edges is None:
            raise ValueError("apply_feedback before any batch: the "
                             "stream has no frozen edges (or model) "
                             "to interpret the rows against")
        labels = np.asarray(labels)
        if len(labels) != len(rows):
            raise ValueError("labels must match the row count")
        fb = self.cfg.feedback
        immediate = fb.filter_enabled if immediate is None else immediate
        online = (fb.dismiss_weight > 0 or fb.confirm_weight > 0) \
            if online is None else online

        words = self._words(rows)
        wid = _bucket_of_keys(words.word_key, self._salt, self.n_buckets)
        benign = labels == BENIGN_LABEL
        n = len(rows)
        stats = {"n_rows": int(n), "n_benign": int(benign.sum())}

        if immediate:
            if self.noise_filter is None:
                self.noise_filter = HostFilter.empty(fb.boost_scale)
            if self.datatype == "flow" and words.ip_u32 is not None \
                    and len(words.ip_u32) == 2 * n:
                pair = pack_pair(words.ip_u32[:n], words.ip_u32[n:])
            elif self.datatype != "flow" and words.ip_u32 is not None:
                pair = np.zeros(n, np.uint64)
                pair[words.event_idx] = pack_pair(
                    words.ip_u32, wid.astype(np.uint32))
            else:
                pair = None     # string-keyed docs: word scope only
            if pair is not None:
                self.noise_filter = self.noise_filter.merged(
                    pair_suppress=pair[benign],
                    pair_boost=pair[~benign])
            else:
                wid_ev = np.zeros(n, np.uint64)
                wid_ev[words.event_idx] = wid[:len(words.event_idx)] \
                    .astype(np.uint64)
                self.noise_filter = self.noise_filter.merged(
                    word_suppress=wid_ev[benign],
                    word_boost=wid_ev[~benign])
            self.feedback_stats["suppress_keys"] = int(
                self.noise_filter.pair_suppress.size
                + self.noise_filter.word_suppress.size)
            self.feedback_stats["boost_keys"] = int(
                self.noise_filter.pair_boost.size
                + self.noise_filter.word_boost.size)

        if online:
            stats.update(self._online_nudge(words, wid, labels))
        self.feedback_stats["applied"] += 1
        return stats

    def _online_nudge(self, words, wid: np.ndarray,
                      labels: np.ndarray) -> dict:
        """Feedback-weighted minibatch through the stream's own SVI
        update: dismissed rows enter at dismiss_weight (the ×DUPFACTOR
        analog — λ and the docs' gamma learn the traffic is normal, so
        p(word|doc) rises and it stops scoring suspicious), confirmed
        rows at confirm_weight (default 0: confirmations must not
        teach the model the attack is common). The minibatch is scaled
        to ITSELF (corpus_docs = its own doc count), never
        extrapolated to the corpus — a handful of weight-1000 rows
        must not deflate every other word's φ."""
        from onix.feedback.filter import BENIGN_LABEL

        fb = self.cfg.feedback
        tok_lab = labels[words.event_idx]       # labels per TOKEN
        weights = np.where(tok_lab == BENIGN_LABEL,
                           np.float32(fb.dismiss_weight),
                           np.float32(fb.confirm_weight))
        keep = weights > 0
        if not keep.any():
            return {"online_steps": 0}
        if isinstance(self.docs, U32DocTable):
            if words.ip_u32 is None:
                # One odd feedback frame (IPv6/malformed rows) must
                # NOT flip a columnar stream's doc table to string
                # keys — that one-way conversion would disable the
                # device word path for the stream's remaining life.
                # Skip the nudge instead (the immediate filter, when
                # on, has already taken effect).
                counters.inc("feedback.nudge_skipped_no_u32")
                return {"online_steps": 0,
                        "skipped": "rows lack u32 doc identities"}
            did = self.docs.ids(words.ip_u32)
        else:
            ips = words.ip
            if ips is None:
                from onix.pipelines.words import u32_to_ips
                ips = u32_to_ips(words.ip_u32)
            did = self.docs.ids(ips)
        self._grow(self.docs.n_docs)
        did, wid_k, weights = did[keep], wid[keep], weights[keep]

        t0 = time.perf_counter()
        pad_to, pad_docs = self._pick_pad(len(did), len(np.unique(did)))
        batch = make_minibatch(did, wid_k, pad_to=pad_to,
                               pad_docs=pad_docs, weights=weights)
        dm = np.asarray(batch.doc_map)
        real = dm >= 0
        k = self._gamma.shape[1]
        g0 = np.full((batch.n_docs, k), self.cfg.lda.alpha + 1.0,
                     np.float32)
        g0[real] = self._gamma[dm[real]]
        steps = 0
        gamma = g0
        for _ in range(fb.online_steps):
            self.state, gamma = self.model.update(
                self.state, batch, corpus_docs=max(float(real.sum()), 2.0),
                gamma0=gamma)
            self.dispatches["svi_update"] += 1
            steps += 1
        gm = np.asarray(gamma)
        self._gamma[dm[real]] = gm[real]
        self.feedback_stats["online_steps"] += steps
        self.stage_walls["svi_update"] += time.perf_counter() - t0
        return {"online_steps": steps, "svi_step": int(self.state.step)}

    def process(self, table: pd.DataFrame,
                cols: dict | None = None) -> BatchResult:
        """Word-create, model-update, and score one minibatch.

        `cols` takes a pre-converted column dict from convert_columns
        (the ColumnPrefetcher hands it over) so the ~30%-of-batch-wall
        frame→columns host conversion (docs/PERF.md r6) that already ran
        under the previous batch's device step is not paid again.

        Chaos hook: a `stream:batch` rule in the active fault plan
        fires HERE, before any scorer state (model, doc table, gamma,
        batch counter) is touched — so a caller that retries the batch
        (run_stream does, bounded) replays it against unchanged state
        and the stream's artifacts are identical to a fault-free run."""
        from onix.utils import faults, telemetry

        # Per-batch trace id (r18), deterministic in the batch counter:
        # a bounded retry replays under the SAME id, so a fault + its
        # replay read as one trace in the flight ring. The fault site
        # fires inside the span — an injected raise closes it as an
        # error span, the postmortem breadcrumb.
        with telemetry.TRACER.trace(f"stream-b{self._batch_no + 1}"), \
                telemetry.TRACER.span("stream.batch", events=len(table)):
            faults.fire("stream", "batch")
            return self._process_one(table, cols)

    def _process_one(self, table: pd.DataFrame,
                     cols: dict | None) -> BatchResult:
        n_events = len(table)
        if n_events == 0:
            return BatchResult(np.empty(0), table.iloc[0:0].copy(), 0, 0,
                               int(self.state.step))
        p = self._prep_batch(table, cols)
        t_stage = time.perf_counter
        t0 = t_stage()
        pad_to, pad_docs = self._pick_pad(p.t_rows, p.n_batch_docs)
        batch = make_minibatch(p.did_b, p.wid_b, pad_to=pad_to,
                               pad_docs=pad_docs, weights=p.weights)
        dm = np.asarray(batch.doc_map)
        real = dm >= 0
        # Warm-start the E-step from each returning doc's LAST gamma —
        # recurring docs (the stream's common case) converge in a few
        # iterations under the meanchange stop instead of re-walking
        # from the prior every batch. First-seen docs start cold.
        k = self._gamma.shape[1]
        g0 = np.full((batch.n_docs, k), self.cfg.lda.alpha + 1.0,
                     np.float32)
        prev = real.copy()
        prev[real] = dm[real] < p.docs_before
        g0[prev] = self._gamma[dm[prev]]
        self.stage_walls["minibatch"] += t_stage() - t0

        t0 = t_stage()
        # Corpus-size estimate for the natural-gradient scale: the docs
        # seen so far (the standard running-D choice for streams).
        self.state, gamma = self.model.update(
            self.state, batch, corpus_docs=max(self.docs.n_docs, 2),
            gamma0=g0)
        gm = np.asarray(gamma)
        self.dispatches["svi_update"] += 1
        self.stage_walls["svi_update"] += t_stage() - t0
        self._gamma[dm[real]] = gm[real]
        self._last_seen[dm[real]] = self._batch_no + 1

        # Incremental scoring of THIS batch's events under the updated
        # model. Only the batch's OWN doc rows are normalized and
        # shipped — the full padded-capacity gamma grows with every doc
        # the stream has ever seen, so using it here would make each
        # batch cost O(total docs) on a long-running stream. Rows are
        # padded to the batch's pow2 doc shape (never-indexed filler at
        # the uniform prior), so the scoring program still compiles
        # once per (token, doc) shape pair, not per batch.
        # dm[real] is the batch's sorted unique global doc ids, and the
        # batch's padded local doc/word id arrays are exactly the token
        # columns scoring needs — make_minibatch already computed all of
        # them; no second unique pass over the tokens.
        t0 = t_stage()
        uniq_d = dm[real]
        theta_b = np.full((pad_docs, k), 1.0 / k, np.float32)
        rows = self._gamma[uniq_d]
        theta_b[:len(uniq_d)] = rows / rows.sum(1, keepdims=True)
        if p.inv is not None:
            # One fused gather-dot program over the unique pairs, then
            # broadcast through the inverse — identical event scores at
            # a fraction of the gathered rows. phi stays device-side.
            import jax.numpy as jnp

            from onix.models.scoring import _score_events_jit
            pair_scores = np.asarray(_score_events_jit(
                jnp.asarray(theta_b), phi_estimate(self.state),
                batch.doc_ids, batch.word_ids))[:p.t_rows]
            tok_scores = pair_scores[p.inv]
        else:
            phi = np.asarray(phi_estimate(self.state))
            tok_scores = score_all(theta_b, phi, np.asarray(batch.doc_ids),
                                   np.asarray(batch.word_ids),
                                   chunk=pad_to)[:p.t]
        self.dispatches["score"] += 1
        self.stage_walls["score"] += t_stage() - t0

        res = self._emit(p, tok_scores, evict=True)
        every = self.cfg.lda.checkpoint_every
        if (self.checkpoint_dir is not None and every > 0
                and self._batch_no % every == 0):
            self.save_checkpoint()
        return res

    def process_many(self, batches: list,
                     superstep: int | None = None) -> list[BatchResult]:
        """Process a list of (table, cols) minibatches in stream order.

        With superstep S > 1 (pipeline.stream_superstep, or the
        explicit override), every group of S batches is ONE fused
        device dispatch: E-step + natural-gradient λ-step +
        incremental scoring for all S batches chained inside one
        jitted program (lda_svi.svi_superstep), warm starts flowing
        batch-to-batch through a device-resident union gamma store,
        and the scores block fetched ONCE per group. S <= 1 degrades
        to per-batch process() calls.

        Semantics vs the per-batch path: identical E-step/λ-step/
        scoring math per batch (winner-set parity asserted in tests);
        eviction and checkpointing land on superstep boundaries, so
        with max_docs set the doc bound gains up to S batches of
        slack before the LRU sweep."""
        s = self.superstep if superstep is None else max(1, superstep)
        if s <= 1:
            return [self.process(t, cols=c) for t, c in batches]
        out: list[BatchResult] = []
        for i in range(0, len(batches), s):
            out.extend(self._process_superstep(batches[i:i + s]))
        return out

    def _process_superstep(self, group: list) -> list[BatchResult]:
        from onix.utils import telemetry

        # Per-group trace id, deterministic in the batch counter (the
        # per-batch analog lives in process()); one fused dispatch =
        # one stream.superstep span.
        with telemetry.TRACER.trace(f"stream-s{self._batch_no + 1}"), \
                telemetry.TRACER.span("stream.superstep",
                                      batches=len(group)):
            return self._process_superstep_traced(group)

    def _process_superstep_traced(self, group: list) -> list[BatchResult]:
        from onix.utils import faults

        # All fault hooks fire BEFORE any scorer state mutates, so a
        # caller retrying the group (run_stream does) replays it
        # against unchanged state — same contract as process().
        for _ in group:
            faults.fire("stream", "batch")
        results: list[BatchResult | None] = [None] * len(group)
        live = []
        for gi, (table, _) in enumerate(group):
            if len(table) == 0:
                results[gi] = BatchResult(np.empty(0),
                                          table.iloc[0:0].copy(), 0, 0,
                                          int(self.state.step))
            else:
                live.append(gi)
        if not live:
            return results
        if len(live) == 1:
            gi = live[0]
            results[gi] = self._process_one(*group[gi])
            return results

        import jax.numpy as jnp

        from onix.models.lda_svi import SuperBatch, minibatch_arrays

        preps = [self._prep_batch(*group[gi]) for gi in live]
        t_stage = time.perf_counter
        t0 = t_stage()
        # One shared static shape for the whole group (the stream's
        # equal-size batches land on one (pad_to, pad_docs) anyway).
        pad_to, pad_docs = self._pick_pad(
            max(p.t_rows for p in preps),
            max(p.n_batch_docs for p in preps))
        self.superstep_shapes.add((len(preps), pad_to, pad_docs))
        k = self._gamma.shape[1]
        arrs = [minibatch_arrays(p.did_b, p.wid_b, pad_to=pad_to,
                                 pad_docs=pad_docs, weights=p.weights)
                for p in preps]
        doc_maps = [a[3] for a in arrs]
        # Union of every global doc the group touches → the device
        # warm-start store. Docs that existed before the superstep
        # start from their live gamma; docs first seen inside the
        # group start cold (alpha+1) exactly as the per-batch g0
        # does — their creating batch is their first toucher, and
        # later batches in the group warm-start from the store row
        # that batch wrote on device.
        union = np.unique(np.concatenate([dm[dm >= 0]
                                          for dm in doc_maps]))
        u = len(union)
        u_pad = _next_pow2(u + 1, floor=64)   # +1: last row = pad dummy
        gamma_union = np.full((u_pad, k), self.cfg.lda.alpha + 1.0,
                              np.float32)
        pre = union < preps[0].docs_before
        gamma_union[:u][pre] = self._gamma[union[pre]]
        dmu = np.full((len(live), pad_docs), -1, np.int32)
        for i, dm in enumerate(doc_maps):
            r = dm >= 0
            dmu[i][r] = np.searchsorted(union, dm[r]).astype(np.int32)
        sb = SuperBatch(
            doc_ids=jnp.asarray(np.stack([a[0] for a in arrs])),
            word_ids=jnp.asarray(np.stack([a[1] for a in arrs])),
            mask=jnp.asarray(np.stack([a[2] for a in arrs])),
            doc_map=jnp.asarray(dmu),
            n_docs=pad_docs)
        corpus = np.maximum(
            np.asarray([p.n_docs_after for p in preps], np.float32), 2.0)
        self.stage_walls["minibatch"] += t_stage() - t0

        t0 = t_stage()
        self.state, store, scores = self.model.update_superstep(
            self.state, sb, gamma_union, corpus)
        scores_h = np.asarray(scores)     # THE one fetch per superstep
        store_h = np.asarray(store)
        self.dispatches["superstep"] += 1
        self.stage_walls["svi_update"] += t_stage() - t0
        self._gamma[union] = store_h[:u]

        bno_before = self._batch_no
        for i, gi in enumerate(live):
            p = preps[i]
            dm = doc_maps[i]
            r = dm >= 0
            self._last_seen[dm[r]] = self._batch_no + 1
            tok = scores_h[i][:p.t_rows]
            if p.inv is not None:
                tok = tok[p.inv]
            results[gi] = self._emit(p, tok, evict=False)
        self._maybe_evict()
        every = self.cfg.lda.checkpoint_every
        if (self.checkpoint_dir is not None and every > 0
                and self._batch_no // every != bno_before // every):
            self.save_checkpoint()
        return results


def _convert_frame(datatype: str, table: pd.DataFrame) -> dict | None:
    """frame → numeric columns, or None for frames the converter
    rejects (those ride the string word path). Module-level so a
    process-pool prefetch worker can run it without pickling a
    scorer."""
    from onix.pipelines import columnar

    conv = columnar.FRAME_COLS[datatype]
    try:
        return conv(table)
    except (ValueError, KeyError):
        return None


def _produce_item(datatype: str, item):
    """Worker-side unit of the prefetch pipeline: materialize the
    frame (callable items run their decode HERE) and convert it.
    Returns (table, cols, produce_wall_s, counter_deltas) — the
    counter deltas exist because a process-pool worker's obs counters
    are process-local and its salvage/skip tallies would otherwise
    vanish; the consumer merges them (process mode only — thread
    workers already increment the shared registry)."""
    before = counters.snapshot()
    t0 = time.perf_counter()
    table = item() if callable(item) else item
    cols = _convert_frame(datatype, table)
    wall = time.perf_counter() - t0
    delta = {k: v - before.get(k, 0) for k, v in counters.snapshot().items()
             if v != before.get(k, 0)}
    return table, cols, wall, delta


class ColumnPrefetcher:
    """Depth-k bounded prefetch pipeline for the streaming host stage.

    The steady-state streaming batch spends ~30% of its wall in the
    frame→columns conversion (docs/PERF.md r6) — pure host
    string/array work that needs no scorer state — and, through
    run_stream, the file decode ahead of it. This iterator runs up to
    `depth` future batches' decode+conversion on worker threads OR
    process-pool workers while the caller processes the current one:

    * **bounded + in-order**: at most `depth` items are in flight
      (backpressure — a slow device stage never piles frames up), and
      handoff is strictly submission-ordered, so scorer state mutates
      in stream order exactly as serial process() calls would.
    * **thread-vs-process auto-pick** (mode="auto", the default): the
      FIRST item is produced inline and timed, its pickle round-trip
      cost measured, and the pipeline picks the process pool only when
      the measured produce wall clears 2× the IPC cost on a multi-core
      host (the pandas/string conversion holds the GIL — threads only
      overlap it where NumPy releases; a worker process sidesteps the
      GIL at the price of shipping the frame). The calibration lands
      in scorer.prefetch_stats. An active fault plan pins the thread
      arm (rule state is process-local; a drill's injected decode
      faults must be marked consumed in the parent).
    * **failure transparency**: a worker exception re-raises at the
      consumer's next handoff (never a hang), and early exit from the
      consuming loop cancels pending work and shuts the pool down.

    `items` yields DataFrames or zero-arg callables returning
    DataFrames (run_stream passes picklable `DecodeItem`s so decode
    rides the worker in either mode). Yields (table, cols) pairs for
    `scorer.process(table, cols=cols)`; cols is None for frames the
    converter rejects. Accounting: stage_walls["prefetch_wait"] is the
    seconds the CONSUMER actually blocked (the only prefetch time that
    extends the pipeline wall — the stage-sum identity tests rely on
    this); "prefetch_overlap" is worker produce wall that ran hidden
    under the device step (informational — with depth > 1 workers also
    overlap each other); queue occupancy and worker busy seconds land
    in scorer.prefetch_stats."""

    def __init__(self, scorer: StreamingScorer, items,
                 depth: int | None = None, mode: str | None = None):
        cfg = scorer.cfg.pipeline
        self.scorer = scorer
        self.items = items
        env_depth = os.environ.get("ONIX_PREFETCH_DEPTH")
        self.depth = max(1, int(
            depth if depth is not None
            else env_depth if env_depth else cfg.stream_prefetch_depth))
        self.mode = (mode or os.environ.get("ONIX_PREFETCH_MODE")
                     or cfg.stream_prefetch_mode)
        if self.mode not in ("auto", "thread", "process"):
            raise ValueError(f"prefetch mode must be auto|thread|process,"
                             f" got {self.mode!r}")

    def _calibrate(self, produced, item0, stats) -> str:
        """Measured thread-vs-process pick from the first item."""
        import pickle

        table, cols, wall, _ = produced
        try:
            t0 = time.perf_counter()
            blob = pickle.dumps((table, cols),
                                protocol=pickle.HIGHEST_PROTOCOL)
            pickle.loads(blob)
            ipc = time.perf_counter() - t0
            if callable(item0):
                # Callable items (decode specs) ship cheaply INTO the
                # pool; only the result pays IPC — but the item must
                # actually pickle (a closure cannot).
                pickle.dumps(item0, protocol=pickle.HIGHEST_PROTOCOL)
            else:
                ipc *= 2.0      # DataFrame items also ship in
        except Exception:       # noqa: BLE001 — unpicklable item/frame
            counters.inc("stream.prefetch_unpicklable")
            stats["calibration"] = {"picked": "thread",
                                    "reason": "unpicklable item"}
            return "thread"
        multi = (os.cpu_count() or 1) > 1
        # Two gates: the produce wall must clear its own IPC cost by
        # 2x, AND be big enough in absolute terms (250 ms/batch —
        # production-scale decode+convert measures 0.3-0.5 s) that the
        # spawn pool's per-worker startup (re-import of the consumer's
        # modules, ~5-10 s) can amortize over the stream. Small-file
        # streams stay on threads.
        picked = ("process" if (multi and wall > 2.0 * ipc
                                and wall > 0.25) else "thread")
        stats["calibration"] = {"produce_wall_s": round(wall, 4),
                                "pickle_roundtrip_s": round(ipc, 4),
                                "picked": picked}
        return picked

    def __iter__(self):
        import concurrent.futures as cf
        # Explicit import: `cf.process` is a lazily-populated
        # submodule — referencing it in an except clause from thread
        # mode would itself AttributeError and mask the worker's real
        # exception.
        from concurrent.futures.process import BrokenProcessPool

        from onix.utils import faults

        dt = self.scorer.datatype
        stats = {"depth": self.depth, "resolves": 0, "occupancy_sum": 0,
                 "occupancy_max": 0, "worker_busy_s": 0.0}
        self.scorer.prefetch_stats = stats
        walls = self.scorer.stage_walls

        it = iter(self.items)
        mode = self.mode
        first = None
        if mode == "auto":
            try:
                item0 = next(it)
            except StopIteration:
                stats["mode"] = "thread"
                return
            first = _produce_item(dt, item0)
            mode = self._calibrate(first, item0, stats)
        if mode == "process" and faults.active_plan() is not None:
            mode = "thread"
            stats["mode_forced_by_fault_plan"] = True
        if mode == "process":
            # Spawned workers re-import the __main__ module from its
            # file; a consumer with no real one (stdin, python -c,
            # interactive) cannot host a spawn pool at all.
            import __main__
            if not getattr(__main__, "__file__", None):
                mode = "thread"
                stats["mode_forced_no_main_file"] = True
        stats["mode"] = mode

        def make_pool(m):
            if m == "process":
                import multiprocessing

                workers = min(self.depth,
                              max(1, (os.cpu_count() or 2) - 1))
                # Spawn, not fork: the consumer process runs JAX,
                # whose background threads make fork-inherited lock
                # state a deadlock hazard. Spawned workers re-import
                # (one-time, amortized over the stream's life by pool
                # persistence).
                return cf.ProcessPoolExecutor(
                    max_workers=workers,
                    mp_context=multiprocessing.get_context("spawn"))
            return cf.ThreadPoolExecutor(
                max_workers=self.depth, thread_name_prefix="onix-prefetch")

        pool = make_pool(mode)
        # (item, future) pairs: decode+convert are pure reads, so a
        # broken process pool can resubmit its in-flight items to a
        # replacement thread pool instead of failing the stream.
        pending: collections.deque = collections.deque()
        try:
            if first is not None:
                # The calibration item ran inline: its wall blocked the
                # consumer, so it is wait, not overlap.
                table, cols, wall, _ = first
                walls["prefetch_wait"] += wall
                stats["worker_busy_s"] += wall
                yield table, cols
            while True:
                while len(pending) < self.depth:
                    try:
                        item = next(it)
                    except StopIteration:
                        break
                    pending.append((item, pool.submit(_produce_item,
                                                      dt, item)))
                if not pending:
                    break
                item, fut = pending.popleft()
                stats["resolves"] += 1
                occ = len(pending) + 1
                stats["occupancy_sum"] += occ
                stats["occupancy_max"] = max(stats["occupancy_max"], occ)
                t0 = time.perf_counter()
                try:
                    table, cols, wall, delta = fut.result()
                except BrokenProcessPool:
                    # A worker died (OOM, spawn failure mid-stream).
                    # Degrade to threads and replay the in-flight
                    # items — pure work, exactly-once handoff intact.
                    counters.inc("stream.prefetch_pool_broken")
                    stats["pool_broken"] = True
                    stats["mode"] = mode = "thread"
                    pool.shutdown(wait=False, cancel_futures=True)
                    pool = make_pool("thread")
                    redo = [item] + [i for i, _ in pending]
                    pending = collections.deque(
                        (i, pool.submit(_produce_item, dt, i))
                        for i in redo)
                    item, fut = pending.popleft()
                    table, cols, wall, delta = fut.result()
                wait = time.perf_counter() - t0
                walls["prefetch_wait"] += wait
                walls["prefetch_overlap"] += max(wall - wait, 0.0)
                stats["worker_busy_s"] += wall
                if mode == "process" and delta:
                    for name, n in delta.items():
                        counters.inc(name, n)
                yield table, cols
        finally:
            for _, fut in pending:
                fut.cancel()
            pool.shutdown(wait=True, cancel_futures=True)


def run_stream(cfg: OnixConfig, datatype: str, paths: list[str],
               n_buckets: int = 1 << 15, epochs: int = 1) -> int:
    """CLI driver: each raw telemetry file is one minibatch — decode,
    update the model, score, append alerts to a per-day streaming CSV.
    Decode + frame→columns conversion of batch i+1 overlap batch i's
    model step via the one-deep ColumnPrefetcher.

    `epochs > 1` replays the file list (useful to burn in a model before
    leaving it running on live data)."""
    from onix.ingest.run import DecodeItem
    from onix.store import results_path

    ck_dir = None
    if cfg.lda.checkpoint_every > 0:
        ck_dir = (pathlib.Path(cfg.store.checkpoint_dir) / datatype
                  / "stream")
    scorer = StreamingScorer(cfg, datatype, n_buckets=n_buckets,
                             checkpoint_dir=ck_dir,
                             max_docs=cfg.pipeline.stream_max_docs or None)
    total_events = 0
    total_alerts = 0
    # Resume skips batches the restored checkpoint already consumed —
    # re-processing them would double-train the model AND re-append
    # their alert rows to the per-day CSVs.
    done = scorer._batch_no
    if done:
        print(f"stream resume: skipping {done} already-processed batches")

    def batches():
        """(epoch, path, DecodeItem) for every batch left to process;
        the item runs on a prefetch worker (thread or process pool —
        DecodeItem is picklable), so file decode AND frame→columns
        conversion ride under earlier batches' device steps."""
        batch_idx = 0
        for epoch in range(epochs):
            for p in paths:
                batch_idx += 1
                if batch_idx <= done:
                    continue
                yield (epoch, p,
                       DecodeItem(datatype, str(p),
                                  apply_sampling=cfg.ingest
                                  .apply_sampling))

    todo = list(batches())
    prefetched = ColumnPrefetcher(scorer, (item for _, _, item in todo))
    # Injected batch faults (the chaos drill) are retried under the
    # shared bounded policy. The retry is restricted to InjectedFault
    # BY DESIGN: the fault hook fires at process()/process_many()
    # entry before any scorer state mutates, so a replay is exact —
    # whereas an arbitrary mid-process error (device OOM during the
    # SVI step) could land after the model/doc-table updates and a
    # blind replay would double-train the batch. Real errors
    # propagate: streams fail loudly, they neither skip telemetry nor
    # double-apply it.
    from onix.utils.faults import InjectedFault
    batch_policy = resilience.RetryPolicy(max_attempts=3,
                                          base_backoff_s=0.05,
                                          max_backoff_s=2.0,
                                          salvage_on_final=False)

    def consume(meta, data):
        nonlocal total_events, total_alerts
        results = resilience.retry_call(
            lambda strict: scorer.process_many(data),
            policy=batch_policy, counter_prefix="stream.batch",
            retry_on=InjectedFault)
        for (epoch, p), res in zip(meta, results):
            total_events += res.n_events
            if epoch == epochs - 1 and len(res.alerts):
                # Alerts land in per-day files keyed like batch results.
                from onix.ingest.run import _day_of
                for date, rows in res.alerts.groupby(
                        _day_of(datatype, res.alerts)):
                    out = results_path(cfg.store.results_dir, datatype,
                                       str(date))
                    out = out.with_name(f"{datatype}_streaming.csv")
                    out.parent.mkdir(parents=True, exist_ok=True)
                    rows.to_csv(out, mode="a", index=False,
                                header=not out.exists())
                    total_alerts += len(rows)
            print(f"[epoch {epoch}] {p}: {res.n_events} events, "
                  f"{len(res.alerts)} alerts, {res.n_new_docs} new docs, "
                  f"svi step {res.step}")

    # Superstep grouping: S prefetched batches go through ONE fused
    # dispatch (process_many). S=1 keeps the per-batch path; either
    # way batches are consumed strictly in stream order.
    group_size = scorer.superstep
    meta_buf: list = []
    data_buf: list = []
    for (epoch, p, _), (table, cols) in zip(todo, prefetched):
        meta_buf.append((epoch, p))
        data_buf.append((table, cols))
        if len(data_buf) >= group_size:
            consume(meta_buf, data_buf)
            meta_buf, data_buf = [], []
    if data_buf:
        consume(meta_buf, data_buf)
    sh = scorer.shape_stats
    print(f"stream done: {total_events} events, {total_alerts} alerts, "
          f"{len(scorer.pad_shapes)} compiled shapes "
          f"({sh['compiled']} compiles, {sh['repadded']} re-padded), "
          f"dispatches {scorer.dispatches}")
    ps = scorer.prefetch_stats
    if ps.get("resolves"):
        print(f"stream prefetch: mode={ps.get('mode')} "
              f"depth={ps['depth']} "
              f"occupancy mean "
              f"{ps['occupancy_sum'] / max(ps['resolves'], 1):.1f}"
              f"/max {ps['occupancy_max']}, "
              f"worker busy {ps['worker_busy_s']:.2f}s, "
              f"wait {scorer.stage_walls['prefetch_wait']:.2f}s, "
              f"overlap {scorer.stage_walls['prefetch_overlap']:.2f}s")
    resil = {**counters.snapshot("stream.batch"),
             **counters.snapshot("faults"),
             **counters.snapshot("salvage")}
    if resil:
        print(f"stream resilience: {resil}")
    return 0
