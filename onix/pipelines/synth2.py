"""Session/state-machine telemetry generator — the INDEPENDENT witness.

Every accuracy number in rounds 1-4 rode `synth.py`, whose background is
a role-MIXTURE: each event draws a hidden profile, then draws features
from that profile's distributions. That is exactly a topic model, so
planted-detection and overlap results on it are partly self-referential
— the model family being validated generated the validation data
(VERDICT r04 missing #1 / next #4; the reference instead ships a canned
real demo day, /root/reference/README.md:50-62, which its docs use as
the integration fixture).

This module generates telemetry from DIFFERENT generative assumptions —
an agent/session/state-machine process that LDA does not model:

  * Traffic is emitted by SESSIONS, not independent events: a flow
    session is a request/response/keepalive exchange sequence whose
    length is geometric, whose sizes are role-dependent (requests
    small-lognormal, responses heavy-tailed lognormal x Pareto), and
    whose packet counts derive from bytes via a packet-size draw —
    none of these couplings exist in `synth.py` (there ipkt and
    bytes-per-packet are independent lognormals).
  * Catalogs are HEAVY-TAILED GRAPHS: Zipf service/site popularity,
    per-client fixed sub-catalogs, a site -> third-party bipartite
    graph (dns/proxy) shared across sites. Document/word frequencies
    therefore come from graph structure, not Dirichlet mixtures.
  * Hours come from a DIURNAL arrival process with per-client
    timezone offsets and within-session spillover, not per-profile
    Gaussians.
  * Anomalies are behavioral CAMPAIGNS (scan, beacon, exfiltration,
    DGA, tunnel, C2) with campaign-level correlations — including
    deliberately hard ones that hide on common ports — not
    single-event feature outliers.

The output columns are schema-identical to `synth.SYNTH_ARRAYS` (same
keys, dtypes, background-first/anomalies-last layout, `anomaly_idx`),
so the entire production pipeline — words -> corpus -> Gibbs -> scoring
-> streaming — runs unchanged; `scale.run_scale(generator="sessions")`
and `rehearsal.run_rehearsal(generator="sessions")` select it. Nothing
below draws a (topic, word) pair: if the detector still surfaces the
planted campaigns here, the evidence no longer assumes its own model.
"""

from __future__ import annotations

import numpy as np

from onix.pipelines.synth import FLOW_PROTO_CLASSES

# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

# Diurnal arrival intensity by hour (enterprise day: overnight trough,
# morning ramp, lunch dip, afternoon peak, evening tail).
_DIURNAL = np.array([.25, .18, .15, .14, .16, .25, .5, 1.1, 2.1, 2.8,
                     2.9, 2.6, 2.2, 2.7, 2.9, 2.8, 2.4, 1.9, 1.4, 1.1,
                     .9, .7, .5, .35])

_SYLL = np.array(["ac", "al", "an", "ar", "ba", "be", "bi", "bo", "ca",
                  "ce", "ci", "co", "da", "de", "di", "do", "du", "el",
                  "en", "er", "fa", "fe", "fi", "fo", "ga", "ge", "go",
                  "ha", "he", "hi", "ho", "in", "ka", "ke", "ki", "ko",
                  "la", "le", "li", "lo", "lu", "ma", "me", "mi", "mo",
                  "mu", "na", "ne", "ni", "no", "nu", "or", "pa", "pe",
                  "pi", "po", "ra", "re", "ri", "ro", "ru", "sa", "se",
                  "si", "so", "su", "ta", "te", "ti", "to", "tu", "un",
                  "va", "ve", "vi", "vo", "wa", "we", "wi", "ya", "yo",
                  "za", "zo"])


def _zipf_weights(n: int, s: float) -> np.ndarray:
    w = 1.0 / np.arange(1, n + 1, dtype=np.float64) ** s
    return w / w.sum()


def _diurnal_hours(rng: np.random.Generator, n: int,
                   tz_shift: np.ndarray | None = None) -> np.ndarray:
    """Hours from the diurnal intensity; optional per-row timezone
    shift (graph structure in time, not per-profile Gaussians)."""
    h = rng.choice(24, size=n, p=_DIURNAL / _DIURNAL.sum())
    h = h + rng.random(n)
    if tz_shift is not None:
        h = h + tz_shift
    return (h % 24.0).astype(np.float32)


def _names(rng: np.random.Generator, n: int, tlds: list[str],
           tld_s: float = 1.4, min_syll: int = 2,
           max_syll: int = 4) -> np.ndarray:
    """Pronounceable low-entropy names: 2-4 syllables + Zipf TLD.
    Returns an object array of unique strings (collisions dropped by
    suffixing a counter)."""
    n_s = rng.integers(min_syll, max_syll + 1, n)
    tld_w = _zipf_weights(len(tlds), tld_s)
    tld = rng.choice(len(tlds), n, p=tld_w)
    out = []
    seen = set()
    for i in range(n):
        stem = "".join(rng.choice(_SYLL, n_s[i]))
        name = f"{stem}.{tlds[tld[i]]}"
        if name in seen:
            name = f"{stem}{len(seen) % 97}.{tlds[tld[i]]}"
        seen.add(name)
        out.append(name)
    return np.asarray(out, dtype=object)


def _rand_strings(rng: np.random.Generator, n: int, lo: int, hi: int,
                  alphabet: str) -> np.ndarray:
    """n random strings of length lo..hi — one vectorized draw, then a
    cheap per-row join (used for per-row-unique anomaly payloads whose
    count is tiny vs the event count)."""
    alpha = np.array(list(alphabet))
    lens = rng.integers(lo, hi + 1, n)
    flat = rng.integers(0, len(alpha), int(lens.sum()))
    out = np.empty(n, dtype=object)
    pos = 0
    for i in range(n):
        out[i] = "".join(alpha[flat[pos:pos + lens[i]]])
        pos += lens[i]
    return out


def _sessions_to_rows(rng: np.random.Generator, n_rows: int,
                      mean_rows_per_session: float, draw_block):
    """Generic session-expansion driver: repeatedly draw blocks of
    sessions (draw_block(k) -> dict of per-session arrays + 'n_rows'
    per session), expand to per-row arrays with np.repeat, and stop at
    n_rows. Returns (per-row session index array pieces concatenated by
    the caller via the returned lists)."""
    blocks = []
    total = 0
    while total < n_rows:
        k = max(1024, int((n_rows - total) / mean_rows_per_session * 1.15))
        blk = draw_block(k)
        total += int(blk["n_rows"].sum())
        blocks.append(blk)
    return blocks


# ---------------------------------------------------------------------------
# flow
# ---------------------------------------------------------------------------

# Service catalog: (dport, proto, req_mu, resp_mu, resp_sd, tail_frac,
#                   exch_p, pkt_mu). Sizes are log-space means; tail_frac
# multiplies responses by a Pareto(1.3) factor with that probability.
_SERVICES = [
    (443,  "TCP", 6.2, 9.5, 1.6, 0.10, 0.45, 1200.0),   # https
    (80,   "TCP", 6.0, 9.0, 1.5, 0.08, 0.50, 1200.0),   # http
    (53,   "UDP", 4.2, 5.2, 0.5, 0.00, 0.85, 180.0),    # dns
    (22,   "TCP", 5.5, 7.5, 1.8, 0.05, 0.30, 700.0),    # ssh
    (25,   "TCP", 7.0, 5.5, 0.8, 0.02, 0.70, 900.0),    # smtp
    (993,  "TCP", 5.8, 8.2, 1.4, 0.05, 0.55, 1000.0),   # imaps
    (3306, "TCP", 5.6, 8.8, 1.7, 0.12, 0.25, 1100.0),   # mysql
    (445,  "TCP", 6.5, 9.8, 1.9, 0.15, 0.35, 1300.0),   # smb
    (123,  "UDP", 4.1, 4.1, 0.2, 0.00, 0.95, 90.0),     # ntp
    (389,  "TCP", 5.2, 6.8, 0.9, 0.02, 0.60, 600.0),    # ldap
    (6443, "TCP", 5.9, 7.8, 1.2, 0.04, 0.40, 900.0),    # k8s api
    (8080, "TCP", 6.0, 8.8, 1.5, 0.08, 0.50, 1150.0),   # alt http
    (3389, "TCP", 6.8, 8.5, 1.3, 0.05, 0.20, 950.0),    # rdp
    (514,  "UDP", 5.9, 4.0, 0.3, 0.00, 0.90, 400.0),    # syslog
    (5432, "TCP", 5.6, 8.6, 1.6, 0.10, 0.25, 1100.0),   # postgres
]
_CLIENT_CATALOG = 6          # fixed per-client service sub-catalog size


def sessions_flow_day_arrays(n_events: int, n_hosts: int = 100_000,
                             n_anomalies: int | None = None,
                             seed: int = 0, **_ignored) -> dict:
    """Flow day from the session state machine. Schema-identical to
    `synth.synth_flow_day_arrays` (keys, dtypes, background-first /
    anomalies-last, `anomaly_idx`, `proto_classes`)."""
    if n_anomalies is None:
        n_anomalies = max(30, n_events // 10_000)
    n_anomalies = min(n_anomalies, n_events)
    rng = np.random.default_rng(seed)
    n_svc = len(_SERVICES)
    dport_of = np.array([s[0] for s in _SERVICES], np.int32)
    proto_of = np.array([FLOW_PROTO_CLASSES.index(s[1])
                         for s in _SERVICES], np.int8)
    req_mu = np.array([s[2] for s in _SERVICES])
    resp_mu = np.array([s[3] for s in _SERVICES])
    resp_sd = np.array([s[4] for s in _SERVICES])
    tailf = np.array([s[5] for s in _SERVICES])
    exch_p = np.array([s[6] for s in _SERVICES])
    pkt_mu = np.array([s[7] for s in _SERVICES])

    # Heavy-tailed client activity; fixed per-client sub-catalogs drawn
    # by global Zipf popularity (graph structure, not a mixture).
    act = rng.lognormal(0.0, 1.2, n_hosts)
    act /= act.sum()
    svc_pop = _zipf_weights(n_svc, 1.25)
    catalog = rng.choice(n_svc, (n_hosts, _CLIENT_CATALOG), p=svc_pop)
    # Within-catalog choice weights: position-decayed (the first
    # catalog entry is the client's main service).
    cat_w = 1.0 / np.arange(1, _CLIENT_CATALOG + 1) ** 1.1
    cat_w /= cat_w.sum()
    # Per-service server pools (172.16/12 internal, a few externals in
    # 198.51.100/24 for web-ish services), Zipf within the pool.
    srv_pool_n = np.clip((12 / np.arange(1, n_svc + 1)).astype(int), 2, 12)
    srv_base = np.uint32((172 << 24) | (16 << 16))
    ext_base = np.uint32((198 << 24) | (51 << 16) | (100 << 8))
    host_base = np.uint32(10 << 24)
    # Client timezone groups (3 offices).
    tz = rng.choice(np.array([-7.0, 0.0, 5.5]), n_hosts,
                    p=[0.25, 0.55, 0.20]).astype(np.float32)

    n_bg = n_events - n_anomalies
    mean_exch = float((1.0 / exch_p * svc_pop / svc_pop.sum()).sum())

    def draw_block(k):
        cli = rng.choice(n_hosts, k, p=act)
        slot = rng.choice(_CLIENT_CATALOG, k, p=cat_w)
        svc = catalog[cli, slot]
        # Geometric exchange count (state machine: request/response
        # pairs then keepalives), capped so one session can't be the
        # whole day.
        exch = np.minimum(rng.geometric(exch_p[svc]), 40)
        srv_i = np.minimum(rng.geometric(0.45, k) - 1, srv_pool_n[svc] - 1)
        external = rng.random(k) < np.where(dport_of[svc] >= 80, 0.35, 0.05)
        srv_ip = np.where(
            external,
            ext_base + ((svc.astype(np.uint32) * 13 + srv_i) % 250) + 1,
            srv_base + (svc.astype(np.uint32) << 8)
            + srv_i.astype(np.uint32) + 1)
        h0 = _diurnal_hours(rng, k, tz[cli])
        eph = rng.integers(32768, 61000, k).astype(np.int32)
        return {"n_rows": 2 * exch, "cli": cli, "svc": svc,
                "srv_ip": srv_ip, "h0": h0, "eph": eph, "exch": exch}

    blocks = _sessions_to_rows(rng, n_bg, 2 * mean_exch, draw_block)

    out = {
        "sip_u32": np.empty(n_events, np.uint32),
        "dip_u32": np.empty(n_events, np.uint32),
        "sport": np.empty(n_events, np.int32),
        "dport": np.empty(n_events, np.int32),
        "proto_id": np.empty(n_events, np.int8),
        "hour": np.empty(n_events, np.float32),
        "ipkt": np.empty(n_events, np.int64),
        "ibyt": np.empty(n_events, np.int64),
    }
    lo = 0
    for blk in blocks:
        if lo >= n_bg:
            break
        f = blk["n_rows"]
        rep = np.repeat(np.arange(len(f)), f)
        # Within-session exchange index j (0..f-1): arange minus each
        # session's start offset.
        starts = np.concatenate([[0], np.cumsum(f)[:-1]])
        j = np.arange(len(rep)) - starts[rep]
        m = min(len(rep), n_bg - lo)
        rep, j = rep[:m], j[:m]
        cli_ip = host_base + blk["cli"][rep].astype(np.uint32)
        srv_ip = blk["srv_ip"][rep]
        svc = blk["svc"][rep]
        is_req = (j % 2) == 0
        # Direction alternates: requests client->server, responses back.
        out["sip_u32"][lo:lo + m] = np.where(is_req, cli_ip, srv_ip)
        out["dip_u32"][lo:lo + m] = np.where(is_req, srv_ip, cli_ip)
        out["sport"][lo:lo + m] = np.where(is_req, blk["eph"][rep],
                                           dport_of[svc])
        out["dport"][lo:lo + m] = np.where(is_req, dport_of[svc],
                                           blk["eph"][rep])
        out["proto_id"][lo:lo + m] = proto_of[svc]
        # First exchange carries the payload sizes; keepalive exchanges
        # (j >= 2) are small in both directions.
        first = j < 2
        mu = np.where(is_req, req_mu[svc], resp_mu[svc])
        sd = np.where(is_req, 0.5, resp_sd[svc])
        byt = np.exp(rng.normal(mu, sd)).astype(np.float64)
        tail = (~is_req) & first & (rng.random(m) < tailf[svc])
        byt[tail] *= rng.pareto(1.3, int(tail.sum())) + 1.0
        keep = ~first
        byt[keep] = np.exp(rng.normal(4.2, 0.4, int(keep.sum())))
        pkt_sz = np.clip(rng.normal(pkt_mu[svc], 250.0), 60.0, 1460.0)
        ibyt = np.maximum(byt, 40.0).astype(np.int64)
        out["ibyt"][lo:lo + m] = ibyt
        # Packets DERIVE from bytes (the coupling synth.py lacks);
        # ceil-division so bytes-per-packet never exceeds the MTU draw.
        psz = pkt_sz.astype(np.int64)
        out["ipkt"][lo:lo + m] = np.maximum(-(-ibyt // psz), 1)
        # Session spillover: each exchange drifts ~36 s.
        out["hour"][lo:lo + m] = np.minimum(
            blk["h0"][rep] + 0.01 * j.astype(np.float32), 23.99)
        lo += m

    # --- campaigns (behavioral, campaign-correlated) ---
    a0 = n_bg
    n_scan = int(n_anomalies * 0.4)
    n_beacon = int(n_anomalies * 0.3)
    n_exfil = n_anomalies - n_scan - n_beacon
    sl = slice(a0, a0 + n_scan)
    # Port scan: few sources, many dsts, ascending low ports, 1 packet.
    scan_src = host_base + rng.choice(n_hosts, max(1, n_scan // 800) + 1)
    out["sip_u32"][sl] = rng.choice(scan_src, n_scan)
    out["dip_u32"][sl] = (srv_base
                          + rng.integers(0, 1 << 16, n_scan).astype(np.uint32))
    out["sport"][sl] = rng.integers(40000, 65000, n_scan)
    out["dport"][sl] = (np.arange(n_scan) % 1024) + 1
    out["proto_id"][sl] = FLOW_PROTO_CLASSES.index("TCP")
    out["hour"][sl] = (2.0 + 0.5 * rng.random(n_scan)) % 24
    out["ipkt"][sl] = 1
    out["ibyt"][sl] = rng.choice(np.array([40, 44, 48, 60]), n_scan)
    bl = slice(a0 + n_scan, a0 + n_scan + n_beacon)
    # Beacon: fixed C2, fixed odd port, near-constant tiny payload,
    # evenly spaced through the WHOLE day (defeats hour profiling).
    c2 = np.uint32((203 << 24) | (113 << 8)) + np.uint32(rng.integers(1, 250))
    beac_src = host_base + rng.choice(n_hosts, max(1, n_beacon // 1500) + 1)
    out["sip_u32"][bl] = rng.choice(beac_src, n_beacon)
    out["dip_u32"][bl] = c2
    out["sport"][bl] = rng.integers(32768, 61000, n_beacon)
    out["dport"][bl] = 4444
    out["proto_id"][bl] = FLOW_PROTO_CLASSES.index("TCP")
    out["hour"][bl] = np.linspace(0, 23.99, n_beacon, dtype=np.float32)
    out["ipkt"][bl] = rng.integers(3, 6, n_beacon)
    out["ibyt"][bl] = 300 + rng.integers(-8, 9, n_beacon)
    xl = slice(a0 + n_scan + n_beacon, n_events)
    # Exfil hiding on 443: one client, one rare external, huge uploads
    # during business hours — only the size/volume words are anomalous.
    exf_src = host_base + np.uint32(rng.integers(0, n_hosts))
    exf_dst = ext_base + np.uint32(253)
    out["sip_u32"][xl] = exf_src
    out["dip_u32"][xl] = exf_dst
    out["sport"][xl] = rng.integers(32768, 61000, n_exfil)
    out["dport"][xl] = 443
    out["proto_id"][xl] = FLOW_PROTO_CLASSES.index("TCP")
    out["hour"][xl] = np.clip(rng.normal(14.0, 2.0, n_exfil), 9, 18)
    xb = np.maximum(np.exp(rng.normal(16.5, 1.0, n_exfil)).astype(np.int64),
                    1 << 20)
    out["ibyt"][xl] = xb
    out["ipkt"][xl] = np.maximum(xb // 1400, 1)

    out["anomaly_idx"] = np.arange(n_bg, n_events, dtype=np.int64)
    out["proto_classes"] = list(FLOW_PROTO_CLASSES)
    return out

# ---------------------------------------------------------------------------
# dns
# ---------------------------------------------------------------------------

_TLDS = ["com", "net", "org", "io", "co", "cloud", "dev"]
_RARE_TLDS = ["info", "top", "xyz"]
_B32 = "abcdefghijklmnopqrstuvwxyz234567"
_HEX = "0123456789abcdef"


def sessions_dns_day_arrays(n_events: int, n_hosts: int = 100_000,
                            n_anomalies: int | None = None,
                            seed: int = 0, **_ignored) -> dict:
    """DNS day from browsing sessions over a site -> third-party
    bipartite graph. Schema-identical to `synth.synth_dns_day_arrays`
    (dictionary-encoded qnames, background-first/anomalies-last)."""
    if n_anomalies is None:
        n_anomalies = max(30, n_events // 10_000)
    n_anomalies = min(n_anomalies, n_events)
    rng = np.random.default_rng(seed ^ 0xD15)
    n_sites = int(np.clip(n_hosts // 20, 300, 4000))
    n_tp = 250
    sites = _names(rng, n_sites, _TLDS)
    # Third parties get service-ish prefixes (cdn/analytics/api pools).
    tp_stub = _names(rng, n_tp, ["com", "net", "cloud"])
    tp_pre = rng.choice(np.array(["cdn", "static", "img", "api",
                                  "metrics", "ads", "fonts"]), n_tp)
    tps = np.asarray([f"{p}.{s}" for p, s in zip(tp_pre, tp_stub)],
                     dtype=object)
    # Typo pool: mutated site names, NXDOMAIN on resolve.
    n_typo = max(8, n_sites // 10)
    typo_src = rng.choice(n_sites, n_typo)
    typos = np.asarray([s[:1] + s[2:] if len(s) > 4 else s + "x"
                        for s in sites[typo_src]], dtype=object)

    # Bipartite site -> partner graph (CSR): heavy-tailed out-degree,
    # partners drawn by Zipf third-party popularity. The SAME partner
    # appears under many sites — co-occurrence from graph structure.
    deg = np.minimum(1 + rng.geometric(0.35, n_sites), 12)
    tp_w = _zipf_weights(n_tp, 1.2)
    part_lo = np.concatenate([[0], np.cumsum(deg)])
    partners = rng.choice(n_tp, int(deg.sum()), p=tp_w)

    site_w = _zipf_weights(n_sites, 1.1)
    act = rng.lognormal(0.0, 1.2, n_hosts)
    act /= act.sum()
    tz = rng.choice(np.array([-7.0, 0.0, 5.5]), n_hosts,
                    p=[0.25, 0.55, 0.20]).astype(np.float32)
    host_base = np.uint32(10 << 24)
    n_bg = n_events - n_anomalies
    mean_q = 1.0 + 0.7 * float(deg.mean())

    def draw_block(k):
        cli = rng.choice(n_hosts, k, p=act)
        site = rng.choice(n_sites, k, p=site_w)
        # 1 site query + each partner with p=0.7 (cache hit rate).
        n_part = rng.binomial(deg[site], 0.7)
        h0 = _diurnal_hours(rng, k, tz[cli])
        typo = rng.random(k) < 0.012
        return {"n_rows": 1 + n_part, "cli": cli, "site": site,
                "h0": h0, "typo": typo}

    blocks = _sessions_to_rows(rng, n_bg, mean_q, draw_block)

    out = {
        "client_u32": np.empty(n_events, np.uint32),
        "qname_codes": np.empty(n_events, np.int64),
        "qtype": np.empty(n_events, np.int32),
        "rcode": np.empty(n_events, np.int32),
        "frame_len": np.empty(n_events, np.int32),
        "hour": np.empty(n_events, np.float32),
    }
    # Unique-name table layout: [sites | tps | typos | anomalies].
    code_tp0 = n_sites
    code_typo0 = n_sites + n_tp
    code_anom0 = code_typo0 + n_typo
    site_len = np.fromiter((len(s) for s in sites), np.int64, n_sites)
    tp_len = np.fromiter((len(s) for s in tps), np.int64, n_tp)
    typo_len = np.fromiter((len(s) for s in typos), np.int64, n_typo)
    all_len = np.concatenate([site_len, tp_len, typo_len])
    typo_of_site = np.full(n_sites, -1, np.int64)
    typo_of_site[typo_src] = np.arange(n_typo)

    lo = 0
    for blk in blocks:
        if lo >= n_bg:
            break
        f = blk["n_rows"]
        rep = np.repeat(np.arange(len(f)), f)
        starts = np.concatenate([[0], np.cumsum(f)[:-1]])
        j = np.arange(len(rep)) - starts[rep]
        m = min(len(rep), n_bg - lo)
        rep, j = rep[:m], j[:m]
        site = blk["site"][rep]
        is_site_q = j == 0
        # Partner queries index the site's CSR row; the j-th partner.
        pidx = part_lo[site] + np.maximum(j - 1, 0) % np.maximum(deg[site], 1)
        codes = np.where(is_site_q, site, code_tp0 + partners[pidx])
        # Typo'd first query where flagged (and a typo exists).
        t_ok = blk["typo"][rep] & is_site_q & (typo_of_site[site] >= 0)
        codes = np.where(t_ok, code_typo0 + typo_of_site[site], codes)
        out["client_u32"][lo:lo + m] = (host_base
                                        + blk["cli"][rep].astype(np.uint32))
        out["qname_codes"][lo:lo + m] = codes
        # A/AAAA mix for browsing; rare MX/TXT infra lookups on site
        # queries only.
        qt = np.where(rng.random(m) < 0.72, 1, 28).astype(np.int32)
        infra = is_site_q & (rng.random(m) < 0.02)
        qt[infra] = rng.choice(np.array([15, 16, 2], np.int32),
                               int(infra.sum()))
        out["qtype"][lo:lo + m] = qt
        rc = np.zeros(m, np.int32)
        rc[t_ok] = 3
        rc[rng.random(m) < 0.004] = 2          # servfail noise
        out["rcode"][lo:lo + m] = rc
        out["frame_len"][lo:lo + m] = (
            28 + all_len[codes] + 14 * (qt == 16).astype(np.int64)
            + rng.integers(0, 8, m)).astype(np.int32)
        out["hour"][lo:lo + m] = np.minimum(
            blk["h0"][rep] + 0.002 * j.astype(np.float32), 23.99)
        lo += m

    # --- campaigns: DGA burst + DNS tunnel ---
    a0 = n_bg
    n_dga = n_anomalies // 2
    n_tun = n_anomalies - n_dga
    dga = _rand_strings(rng, n_dga, 12, 20, _B32)
    dga_tld = rng.choice(np.asarray(_RARE_TLDS, object), n_dga)
    dga_names = np.asarray([f"{s}.{t}" for s, t in zip(dga, dga_tld)],
                           dtype=object)
    tun_apex = "".join(rng.choice(_SYLL, 3)) + ".link"
    tun_sub = _rand_strings(rng, n_tun, 30, 60, _HEX)
    tun_names = np.asarray([f"{s}.{tun_apex}" for s in tun_sub],
                           dtype=object)
    dl = slice(a0, a0 + n_dga)
    dga_cli = host_base + rng.choice(n_hosts, max(1, n_dga // 2000) + 1)
    out["client_u32"][dl] = rng.choice(dga_cli, n_dga)
    out["qname_codes"][dl] = code_anom0 + np.arange(n_dga)
    out["qtype"][dl] = 1
    out["rcode"][dl] = 3                      # NXDOMAIN storm
    out["frame_len"][dl] = (28 + np.fromiter((len(s) for s in dga_names),
                                             np.int64, n_dga)
                            + rng.integers(0, 6, n_dga)).astype(np.int32)
    out["hour"][dl] = (3.0 + rng.random(n_dga) * 1.5) % 24
    tl = slice(a0 + n_dga, n_events)
    tun_cli = host_base + np.uint32(rng.integers(0, n_hosts))
    out["client_u32"][tl] = tun_cli
    out["qname_codes"][tl] = code_anom0 + n_dga + np.arange(n_tun)
    out["qtype"][tl] = np.where(rng.random(n_tun) < 0.8, 16, 10)
    out["rcode"][tl] = 0
    out["frame_len"][tl] = (60 + 4 * np.fromiter(
        (len(s) for s in tun_names), np.int64, n_tun)).astype(np.int32)
    out["hour"][tl] = np.linspace(0, 23.99, n_tun, dtype=np.float32)

    out["qnames"] = np.concatenate([sites, tps, typos, dga_names,
                                    tun_names])
    out["anomaly_idx"] = np.arange(n_bg, n_events, dtype=np.int64)
    return out


# ---------------------------------------------------------------------------
# proxy
# ---------------------------------------------------------------------------

_PAGE_SEGS = np.array(["index", "home", "products", "docs", "blog",
                       "search", "login", "account", "cart", "api/v1",
                       "api/v2", "news", "help", "download", "admin"])
_ASSET_PATHS = np.array([
    "/js/app.min.js", "/js/vendor.js", "/css/site.css", "/css/theme.css",
    "/img/logo.png", "/img/hero.jpg", "/fonts/r.woff2", "/favicon.ico",
    "/js/analytics.js", "/img/sprite.svg", "/css/print.css",
    "/js/jquery.min.js", "/img/banner.webp", "/fonts/b.woff2"])
_UAS = np.array([
    "Mozilla/5.0 (Windows NT 10.0; Win64; x64) Chrome/120.0",
    "Mozilla/5.0 (Windows NT 10.0; Win64; x64) Edge/120.0",
    "Mozilla/5.0 (Macintosh; Intel Mac OS X 10_15_7) Safari/605.1",
    "Mozilla/5.0 (Macintosh; Intel Mac OS X 10_15_7) Chrome/119.0",
    "Mozilla/5.0 (X11; Linux x86_64) Firefox/121.0",
    "Mozilla/5.0 (iPhone; CPU iPhone OS 17_0) Mobile/15E148",
    "Mozilla/5.0 (Linux; Android 14) Chrome/120.0 Mobile",
    "Mozilla/5.0 (Windows NT 10.0; WOW64; rv:109.0) Firefox/115.0",
    "Mozilla/5.0 (Windows NT 6.1; Win64; x64) Chrome/109.0",
    "Mozilla/5.0 (X11; Ubuntu; Linux x86_64) Firefox/120.0",
    "curl/8.4.0",
    "python-requests/2.31.0",
    "Go-http-client/2.0",
    "okhttp/4.12.0"])
_N_BROWSER_UAS = 10              # the tail of _UAS is automation


def sessions_proxy_day_arrays(n_events: int, n_hosts: int = 100_000,
                              n_anomalies: int | None = None,
                              seed: int = 0, **_ignored) -> dict:
    """Proxy day from page-graph browsing sessions. Schema-identical to
    `synth.synth_proxy_day_arrays`."""
    if n_anomalies is None:
        n_anomalies = max(30, n_events // 10_000)
    n_anomalies = min(n_anomalies, n_events)
    rng = np.random.default_rng(seed ^ 0xA11)
    n_sites = int(np.clip(n_hosts // 25, 200, 2000))
    n_cdn = 120
    site_stub = _names(rng, n_sites, _TLDS)
    site_hosts = np.asarray([f"www.{s}" for s in site_stub], dtype=object)
    cdn_stub = _names(rng, n_cdn, ["com", "net", "cloud"])
    cdn_hosts = np.asarray(
        [f"{p}.{s}" for p, s in zip(
            rng.choice(np.array(["cdn", "static", "assets", "media"]),
                       n_cdn), cdn_stub)], dtype=object)

    # Per-site page pools: a subset of the global segment grammar.
    pages: list[str] = []
    page_lo = np.zeros(n_sites + 1, np.int64)
    for s in range(n_sites):
        k = int(rng.integers(3, 10))
        segs = rng.choice(_PAGE_SEGS, k, replace=False)
        pages.append("/")
        pages.extend(f"/{seg}" for seg in segs)
        page_lo[s + 1] = len(pages)
    pages_arr = np.asarray(pages, dtype=object)
    n_pages_of = np.diff(page_lo)
    # Site -> cdn partners (2-4 each), Zipf cdn popularity.
    cdn_w = _zipf_weights(n_cdn, 1.2)
    cdeg = rng.integers(2, 5, n_sites)
    cpart_lo = np.concatenate([[0], np.cumsum(cdeg)])
    cpartners = rng.choice(n_cdn, int(cdeg.sum()), p=cdn_w)

    site_w = _zipf_weights(n_sites, 1.1)
    act = rng.lognormal(0.0, 1.2, n_hosts)
    act /= act.sum()
    tz = rng.choice(np.array([-7.0, 0.0, 5.5]), n_hosts,
                    p=[0.25, 0.55, 0.20]).astype(np.float32)
    # Per-client fixed UA; ~3% automation clients use the tool UAs and
    # only hit api pages.
    ua_w = _zipf_weights(_N_BROWSER_UAS, 1.3)
    ua_of = rng.choice(_N_BROWSER_UAS, n_hosts, p=ua_w)
    bots = rng.random(n_hosts) < 0.03
    ua_of[bots] = _N_BROWSER_UAS + rng.choice(
        len(_UAS) - _N_BROWSER_UAS, int(bots.sum()))
    host_base = np.uint32(10 << 24)
    n_bg = n_events - n_anomalies
    mean_rows = (1 + 0.4) * (1 + 4.0)   # pages x (page + assets)

    def draw_block(k):
        cli = rng.choice(n_hosts, k, p=act)
        site = rng.choice(n_sites, k, p=site_w)
        n_page = np.minimum(rng.geometric(0.55, k), 8)
        n_asset = rng.poisson(4.0, k)
        h0 = _diurnal_hours(rng, k, tz[cli])
        return {"n_rows": n_page * (1 + n_asset) , "cli": cli,
                "site": site, "h0": h0, "n_asset": n_asset}

    blocks = _sessions_to_rows(rng, n_bg, mean_rows, draw_block)

    out = {
        "client_u32": np.empty(n_events, np.uint32),
        "uri_codes": np.empty(n_events, np.int64),
        "host_codes": np.empty(n_events, np.int64),
        "ua_codes": np.empty(n_events, np.int64),
        "respcode": np.empty(n_events, np.int32),
        "hour": np.empty(n_events, np.float32),
    }
    # URI table: [site pages | asset paths | anomalies];
    # host table: [site hosts | cdn hosts | anomalies].
    uri_asset0 = len(pages_arr)
    host_cdn0 = n_sites
    lo = 0
    for blk in blocks:
        if lo >= n_bg:
            break
        f = blk["n_rows"]
        rep = np.repeat(np.arange(len(f)), f)
        starts = np.concatenate([[0], np.cumsum(f)[:-1]])
        j = np.arange(len(rep)) - starts[rep]
        m = min(len(rep), n_bg - lo)
        rep, j = rep[:m], j[:m]
        site = blk["site"][rep]
        per_page = 1 + blk["n_asset"][rep]
        page_i = j // np.maximum(per_page, 1)
        is_page = (j % np.maximum(per_page, 1)) == 0
        bot = ua_of[blk["cli"][rep]] >= _N_BROWSER_UAS
        # Page rows: a URI from the site's pool (bots pin api-ish last
        # entries); asset rows: global asset path on a partner cdn.
        pg = page_lo[site] + (rng.integers(0, 1 << 30, m)
                              + 7 * page_i) % n_pages_of[site]
        pg_bot = page_lo[site] + n_pages_of[site] - 1
        pg = np.where(bot, pg_bot, pg)
        asset = uri_asset0 + rng.integers(0, len(_ASSET_PATHS), m)
        out["uri_codes"][lo:lo + m] = np.where(is_page, pg, asset)
        cdn_pick = cpart_lo[site] + (j % np.maximum(cdeg[site], 1))
        out["host_codes"][lo:lo + m] = np.where(
            is_page, site, host_cdn0 + cpartners[cdn_pick])
        out["client_u32"][lo:lo + m] = (host_base
                                        + blk["cli"][rep].astype(np.uint32))
        out["ua_codes"][lo:lo + m] = ua_of[blk["cli"][rep]]
        rc = np.full(m, 200, np.int32)
        u = rng.random(m)
        rc[u < 0.10] = 304
        rc[u < 0.045] = 302
        rc[u < 0.02] = 404
        rc[u < 0.004] = 500
        out["respcode"][lo:lo + m] = rc
        out["hour"][lo:lo + m] = np.minimum(
            blk["h0"][rep] + 0.003 * j.astype(np.float32), 23.99)
        lo += m

    # --- campaigns: C2 beacon + URI exfil ---
    a0 = n_bg
    n_c2 = n_anomalies // 2
    n_exf = n_anomalies - n_c2
    c2_host = "".join(rng.choice(_SYLL, 3)) + ".top"
    exf_host = "".join(rng.choice(_SYLL, 3)) + ".xyz"
    exf_uris = np.asarray(
        [f"/up?d={s}" for s in _rand_strings(rng, n_exf, 40, 80, _B32)],
        dtype=object)
    n_hosts_tbl = n_sites + n_cdn
    n_uris_tbl = uri_asset0 + len(_ASSET_PATHS)
    cl = slice(a0, a0 + n_c2)
    c2_cli = host_base + rng.choice(n_hosts, max(1, n_c2 // 1500) + 1)
    out["client_u32"][cl] = rng.choice(c2_cli, n_c2)
    out["uri_codes"][cl] = n_uris_tbl            # single "/gate.php"
    out["host_codes"][cl] = n_hosts_tbl
    out["ua_codes"][cl] = 0                      # blends with top UA
    out["respcode"][cl] = 200
    out["hour"][cl] = np.linspace(0, 23.99, n_c2, dtype=np.float32)
    xl = slice(a0 + n_c2, n_events)
    out["client_u32"][xl] = host_base + np.uint32(rng.integers(0, n_hosts))
    out["uri_codes"][xl] = n_uris_tbl + 1 + np.arange(n_exf)
    out["host_codes"][xl] = n_hosts_tbl + 1
    out["ua_codes"][xl] = 0
    out["respcode"][xl] = 200
    out["hour"][xl] = np.clip(rng.normal(14.0, 2.5, n_exf), 8, 19)

    out["uris"] = np.concatenate(
        [pages_arr, _ASSET_PATHS.astype(object),
         np.asarray(["/gate.php"], object), exf_uris])
    out["hosts"] = np.concatenate(
        [site_hosts, cdn_hosts,
         np.asarray([c2_host, exf_host], object)])
    out["agents"] = _UAS.astype(object)
    out["anomaly_idx"] = np.arange(n_bg, n_events, dtype=np.int64)
    return out


SYNTH2_ARRAYS = {"flow": sessions_flow_day_arrays,
                 "dns": sessions_dns_day_arrays,
                 "proxy": sessions_proxy_day_arrays}


# ---------------------------------------------------------------------------
# pandas day frames (store/demo surface)
# ---------------------------------------------------------------------------

def _day_frame(datatype: str, cols: dict, date: str, rng):
    """Render columnar session arrays into the store's day-frame schema
    (same columns as synth.synth_*_day) so `onix demo --generator
    sessions` and store-backed scoring run on the independent data."""
    import pandas as pd

    from onix.pipelines.synth import _shuffle, _times
    from onix.pipelines.words import u32_to_ips

    n = len(cols["hour"])
    n_bg = n - len(cols["anomaly_idx"])
    if datatype == "flow":
        proto_tbl = np.asarray(cols["proto_classes"], dtype=object)
        table = pd.DataFrame({
            "treceived": _times(date, cols["hour"]),
            "sip": u32_to_ips(cols["sip_u32"]),
            "dip": u32_to_ips(cols["dip_u32"]),
            "sport": cols["sport"].astype(np.int32),
            "dport": cols["dport"].astype(np.int32),
            "proto": proto_tbl[cols["proto_id"]],
            "ipkt": cols["ipkt"],
            "ibyt": cols["ibyt"],
            # Reverse-direction columns aren't modeled per-exchange;
            # the ack-heavy response ratio stands in (synth.py uses the
            # same approximation).
            "opkt": (cols["ipkt"] * 0.8).astype(np.int64),
            "obyt": (cols["ibyt"] * 0.3).astype(np.int64),
        })
    elif datatype == "dns":
        names = np.asarray(cols["qnames"], dtype=object)
        table = pd.DataFrame({
            "frame_time": _times(date, cols["hour"]),
            "frame_len": cols["frame_len"],
            "ip_dst": u32_to_ips(cols["client_u32"]),
            "dns_qry_name": names[cols["qname_codes"]],
            "dns_qry_type": cols["qtype"],
            "dns_qry_rcode": cols["rcode"],
        })
    elif datatype == "proxy":
        uris = np.asarray(cols["uris"], dtype=object)
        hosts = np.asarray(cols["hosts"], dtype=object)
        agents = np.asarray(cols["agents"], dtype=object)
        uri_rows = uris[cols["uri_codes"]]
        # Columns outside the word recipe (method/content-type/bytes)
        # get schema-plausible values derived from the session columns.
        is_api = np.char.find(uri_rows.astype(str), "/api") >= 0
        ctype = np.where(is_api, "application/json", "text/html")
        times = _times(date, cols["hour"])
        table = pd.DataFrame({
            "p_date": np.full(n, date),
            "p_time": [t.split(" ")[1] for t in times],
            "clientip": u32_to_ips(cols["client_u32"]),
            "host": hosts[cols["host_codes"]],
            "reqmethod": np.where(is_api, "POST", "GET").astype(object),
            "useragent": agents[cols["ua_codes"]],
            "resconttype": ctype.astype(object),
            "respcode": cols["respcode"].astype(np.int32),
            "uripath": uri_rows,
            "csbytes": (180 + 12 * np.char.str_len(
                uri_rows.astype(str))).astype(np.int64),
            "scbytes": np.exp(rng.normal(7, 1, n)).astype(np.int64),
        })
    else:
        raise ValueError(f"unknown datatype {datatype!r}")
    return _shuffle(table, n_bg, n, rng)


def sessions_flow_day(n_events: int = 20000, n_hosts: int = 120,
                      n_anomalies: int = 30, date: str = "2016-07-08",
                      seed: int = 0):
    cols = sessions_flow_day_arrays(n_events, n_hosts=n_hosts,
                                    n_anomalies=n_anomalies, seed=seed)
    return _day_frame("flow", cols, date,
                      np.random.default_rng(seed ^ 0x5F))


def sessions_dns_day(n_events: int = 20000, n_hosts: int = 120,
                     n_anomalies: int = 30, date: str = "2016-07-08",
                     seed: int = 0):
    cols = sessions_dns_day_arrays(n_events, n_hosts=n_hosts,
                                   n_anomalies=n_anomalies, seed=seed)
    return _day_frame("dns", cols, date,
                      np.random.default_rng(seed ^ 0x5F))


def sessions_proxy_day(n_events: int = 20000, n_hosts: int = 120,
                       n_anomalies: int = 30, date: str = "2016-07-08",
                       seed: int = 0):
    cols = sessions_proxy_day_arrays(n_events, n_hosts=n_hosts,
                                     n_anomalies=n_anomalies, seed=seed)
    return _day_frame("proxy", cols, date,
                      np.random.default_rng(seed ^ 0x5F))


SYNTH2 = {"flow": sessions_flow_day, "dns": sessions_dns_day,
          "proxy": sessions_proxy_day}
