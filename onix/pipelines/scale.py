"""Scale demonstration: a full synthetic flow day end-to-end at 10⁸+ rows.

BASELINE.json configs[3] is "1B-row synthetic netflow, 20 topics,
multi-chip doc-sharded Gibbs, faster end-to-end than the 20-node MPI
baseline" (the reference's own scale claim is "filter billion of events
to a few thousands", README.md:42). This runner executes the WHOLE
pipeline — columnar synthesis → packed word creation → integer corpus
build → sharded Gibbs → scoring scan → bottom-k — with per-stage
wall-clock recorded into a manifest artifact.

Every stage is the production code path: `flow_words_from_arrays` /
`build_corpus` (zero per-row Python), `ShardedGibbsLDA` (the psum
engine), `select_suspicious_events` (fused device score+pair-min+
bottom-k — only the winners cross the device tunnel). Nothing here is
a special-cased benchmark kernel.
"""

from __future__ import annotations

import json
import pathlib
import time

import numpy as np

from onix.config import LDAConfig
from onix.pipelines.corpus_build import build_corpus, select_suspicious_events
from onix.pipelines.synth import synth_flow_day_arrays
from onix.pipelines.words import flow_words_from_arrays


def run_scale(n_events: int, n_hosts: int | None = None,
              n_anomalies: int | None = None, n_sweeps: int = 20,
              n_topics: int = 20, max_results: int = 3000, seed: int = 0,
              out_path: str | pathlib.Path | None = None) -> dict:
    """End-to-end scale run; returns (and optionally writes) the manifest."""
    import jax

    from onix.parallel.mesh import make_mesh
    from onix.parallel.sharded_gibbs import ShardedGibbsLDA
    from onix.utils.obs import enable_compile_cache

    # Cold compiles through the device tunnel run 25-40s per program;
    # persist them so scale runs measure the pipeline, not the compiler.
    # Per-host tempdir location (override: ONIX_JAX_CACHE), NOT a
    # cwd-relative path — the runner is invoked from anywhere.
    import os
    import tempfile
    enable_compile_cache(os.environ.get(
        "ONIX_JAX_CACHE",
        pathlib.Path(tempfile.gettempdir()) / "onix-jax-cache"))

    if n_hosts is None:
        n_hosts = max(120, min(200_000, n_events // 500))
    if n_anomalies is None:
        # Sublinear in n: at 10^8+, a linear anomaly count concentrates
        # enough repeated signature words that the sampler gives the
        # attack its own topic and the events stop being low-probability
        # (the planted-anomaly contract assumes heterogeneity).
        n_anomalies = max(30, min(1000, n_events // 10_000))
    walls: dict[str, float] = {}
    t_all = time.monotonic()

    t = time.monotonic()
    cols = synth_flow_day_arrays(n_events, n_hosts=n_hosts,
                                 n_anomalies=n_anomalies, seed=seed)
    walls["synthesize"] = time.monotonic() - t

    t = time.monotonic()
    wt = flow_words_from_arrays(
        **{k: cols[k] for k in ("sip_u32", "dip_u32", "sport", "dport",
                                "proto_id", "hour", "ibyt", "ipkt")},
        proto_classes=cols["proto_classes"])
    walls["word_creation"] = time.monotonic() - t

    t = time.monotonic()
    bundle = build_corpus(wt)
    corpus = bundle.corpus
    walls["corpus_build"] = time.monotonic() - t

    t = time.monotonic()
    n_dev = len(jax.devices())
    cfg = LDAConfig(n_topics=n_topics, n_sweeps=n_sweeps,
                    burn_in=max(1, n_sweeps // 2),
                    block_size=1 << 16, seed=seed)
    mesh = make_mesh(dp=n_dev, mp=1)
    model = ShardedGibbsLDA(cfg, corpus.n_vocab, mesh=mesh)
    fit = model.fit(corpus)
    theta, phi_wk = fit["theta"], fit["phi_wk"]  # host np arrays: synced
    walls["gibbs_fit"] = time.monotonic() - t

    t = time.monotonic()
    # Fused device path: score -> pair-min -> bottom-k in one compiled
    # scan; only the winners cross the tunnel (corpus_build strategy).
    top = select_suspicious_events(bundle, theta, phi_wk, n_events,
                                   tol=1.0, max_results=max_results)
    top_idx = np.asarray(top.indices)
    walls["score_select"] = time.monotonic() - t

    walls["total"] = time.monotonic() - t_all
    planted = set(cols["anomaly_idx"].tolist())
    hits = len(planted & set(top_idx[top_idx >= 0].tolist()))
    manifest = {
        "config": "BASELINE configs[3] scale demo (synthetic flow day)",
        "n_events": n_events,
        "n_hosts": n_hosts,
        "n_docs": int(corpus.n_docs),
        "n_vocab": int(corpus.n_vocab),
        "n_tokens": int(corpus.n_tokens),
        "n_topics": n_topics,
        "n_sweeps": n_sweeps,
        "devices": [str(d) for d in jax.devices()],
        "mesh": dict(mesh.shape),
        "walls_seconds": {k: round(v, 2) for k, v in walls.items()},
        "events_per_second_end_to_end": round(n_events / walls["total"], 1),
        "planted_anomalies": len(planted),
        "planted_in_bottom_k": hits,
        "max_results": max_results,
        "seed": seed,
    }
    if out_path is not None:
        out_path = pathlib.Path(out_path)
        out_path.parent.mkdir(parents=True, exist_ok=True)
        out_path.write_text(json.dumps(manifest, indent=2) + "\n")
    return manifest


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="onix scale demo — end-to-end synthetic flow day")
    ap.add_argument("--events", type=float, default=1e8)
    ap.add_argument("--hosts", type=int, default=None)
    ap.add_argument("--sweeps", type=int, default=20)
    ap.add_argument("--out", default=None)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    m = run_scale(int(args.events), n_hosts=args.hosts,
                  n_sweeps=args.sweeps, seed=args.seed, out_path=args.out)
    print(json.dumps(m, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
