"""Scale demonstration: a full synthetic telemetry day end-to-end at 10⁸+ rows.

BASELINE.json configs[3] is "1B-row synthetic netflow, 20 topics,
multi-chip doc-sharded Gibbs, faster end-to-end than the 20-node MPI
baseline" (the reference's own scale claim is "filter billion of events
to a few thousands", README.md:42); configs[1]/[2] are the DNS and
proxy SuspiciousConnects paths, which this runner exercises at the same
scale (`datatype=`). It executes the WHOLE pipeline — columnar
synthesis → packed word creation → integer corpus build → sharded
Gibbs → scoring scan → bottom-k — with per-stage wall-clock recorded
into a manifest artifact.

Every stage is the production code path: `*_words_from_arrays` /
`build_corpus` (zero per-row Python), `ShardedGibbsLDA` (the psum
engine), `select_suspicious_events` (fused device score + pair-min /
gather + bottom-k — only the winners cross the device tunnel). Nothing
here is a special-cased benchmark kernel.
"""

from __future__ import annotations

import json
import os
import pathlib
import tempfile
import time

import numpy as np

from onix.config import LDAConfig
from onix.pipelines.device_words import host_words_forced
from onix.pipelines.corpus_build import build_corpus, select_suspicious_events
from onix.pipelines.synth import SYNTH_ARRAYS
from onix.pipelines.words import (dns_words_from_arrays,
                                  flow_words_from_arrays,
                                  proxy_words_from_arrays)

_FLOW_COLS = ("sip_u32", "dip_u32", "sport", "dport", "proto_id", "hour",
              "ibyt", "ipkt")
_DNS_COLS = ("client_u32", "qname_codes", "qnames", "qtype", "rcode",
             "frame_len", "hour")
_PROXY_COLS = ("client_u32", "uri_codes", "uris", "host_codes", "hosts",
               "ua_codes", "agents", "respcode", "hour")


def _words_from_cols(datatype: str, cols: dict, edges: dict | None = None):
    """Columnar word creation for any datatype — always the
    *_words_from_arrays production path (zero per-row Python)."""
    if datatype == "flow":
        return flow_words_from_arrays(
            **{k: cols[k] for k in _FLOW_COLS},
            proto_classes=cols["proto_classes"], edges=edges)
    if datatype == "dns":
        return dns_words_from_arrays(
            **{k: cols[k] for k in _DNS_COLS}, edges=edges)
    if datatype == "proxy":
        return proxy_words_from_arrays(
            **{k: cols[k] for k in _PROXY_COLS}, edges=edges)
    raise ValueError(f"unknown datatype {datatype!r}")


def run_scale(n_events: int, n_hosts: int | None = None,
              n_anomalies: int | None = None, n_sweeps: int = 20,
              n_topics: int = 20, max_results: int = 3000, seed: int = 0,
              train_events: int | None = None, datatype: str = "flow",
              n_chains: int = 1, resume_dir: str | None = None,
              generator: str = "mixture", merge_form: str = "sync",
              merge_staleness: int = 1, fit_hosts: int = 1,
              rebalance: bool = False,
              out_path: str | pathlib.Path | None = None) -> dict:
    """End-to-end scale run; returns (and optionally writes) the manifest.

    With `train_events < n_events` the run demonstrates the full 10⁹
    configuration on bounded hardware: the model is fitted on the first
    `train_events` events (a 2×10⁹-token assignment state does not fit
    one chip's HBM — distributing it across dp shards is exactly what
    the sharded engine does at pod scale, validated by the multichip
    dryrun), then EVERY event of the day streams through the fused
    device scorer in train_events-sized chunks. Events whose word or
    document never occurred in the training window score at prior
    rarity (an unseen word is rarer than the rarest seen word; an
    unseen document gets the uniform α-prior mixture) — the suspicious
    direction, which is the correct failure mode for novel behavior.
    """
    import jax

    from onix.models.lda_gibbs import merge_fingerprint as _merge_fp
    from onix.parallel.mesh import make_mesh
    from onix.parallel.sharded_gibbs import ShardedGibbsLDA
    from onix.utils.obs import enable_compile_cache

    # Cold compiles through the device tunnel run 25-40s per program;
    # persist them so scale runs measure the pipeline, not the compiler.
    # Per-host tempdir location (override: ONIX_JAX_CACHE), NOT a
    # cwd-relative path — the runner is invoked from anywhere.
    enable_compile_cache(os.environ.get(
        "ONIX_JAX_CACHE",
        pathlib.Path(tempfile.gettempdir()) / "onix-jax-cache"))

    if not train_events:          # None or 0: train on everything
        train_events = n_events
    train_events = min(train_events, n_events)
    if n_hosts is None:
        n_hosts = max(120, min(200_000, n_events // 500))
    if n_anomalies is None:
        n_anomalies = _default_anomalies(train_events)
    walls: dict[str, float] = {}
    t_all = time.monotonic()
    ckpt = None
    prior_elapsed = 0.0
    resumed_sessions = 0
    if resume_dir is not None:
        ckpt = _ResumeState(resume_dir, {
            "n_events": n_events, "train_events": train_events,
            "n_hosts": n_hosts, "n_anomalies": n_anomalies,
            "n_sweeps": n_sweeps, "n_topics": n_topics, "seed": seed,
            "datatype": datatype, "n_chains": n_chains,
            "max_results": max_results, "generator": generator,
            "words_mode": "host" if host_words_forced() else "device",
            # r21: a single-process fit and a multi-host fabric fit are
            # different models for τ>0 (and a different checkpoint
            # topology for any τ), so crossing fit_hosts starts clean.
            "fit_hosts": fit_hosts,
            # r14: the merge arm changes the fitted model for τ>0 (and
            # the spec refuses crossing even the bit-identical τ=0), so
            # a resume across a merge-form/τ change starts clean — the
            # SHARED identity rule, so the stage cache and the fit
            # checkpoint can never disagree about what "same run" means.
            **_merge_fp(merge_form, merge_staleness),
        })
        meta = ckpt.load("meta")
        if meta is not None:
            prior_elapsed = float(meta["elapsed"])
            resumed_sessions = int(meta["sessions"])

    t = time.monotonic()
    # generator="sessions" swaps in the INDEPENDENT session/state-
    # machine generator (synth2.py) whose generative assumptions the
    # model family does NOT share — the anti-self-referential witness
    # (VERDICT r04 next #4). Same schema, same pipeline, same planted
    # contract.
    if generator == "sessions":
        from onix.pipelines.synth2 import SYNTH2_ARRAYS as gen_arrays
    elif generator == "mixture":
        gen_arrays = SYNTH_ARRAYS
    else:
        # A typo'd generator silently producing MIXTURE data would
        # stamp independent-witness claims on evidence that isn't.
        raise ValueError(f"unknown generator {generator!r}; "
                         "expected 'mixture' or 'sessions'")
    cols = gen_arrays[datatype](train_events, n_hosts=n_hosts,
                                n_anomalies=n_anomalies, seed=seed)
    walls["synthesize"] = time.monotonic() - t

    t = time.monotonic()
    wt = _words_from_cols(datatype, cols)
    walls["word_creation"] = time.monotonic() - t

    t = time.monotonic()
    bundle = build_corpus(wt)
    corpus = bundle.corpus
    walls["corpus_build"] = time.monotonic() - t

    t = time.monotonic()
    n_dev = len(jax.devices())
    from onix.models.lda_gibbs import SUPERSTEP_DEFAULT

    # n_chains > 1: the judged restart-ensemble estimator on the
    # multi-chip engine (chain axis vmapped per device; the streaming
    # score path geometric-merges the chains in score_table) — the
    # north-star combination "1B multi-chip AND the ensemble the 0.95
    # overlap bar rides" in one config.
    cfg = LDAConfig(n_topics=n_topics, n_sweeps=n_sweeps,
                    burn_in=max(1, n_sweeps // 2),
                    # 2^17 measured fastest on v5e (36.8M tokens/s vs
                    # 33.8M at 2^16, 26.5M at 2^18).
                    block_size=1 << 17, seed=seed, n_chains=n_chains,
                    # r14 count-merge arm: "async" swaps the full-
                    # barrier psum fold for the bounded-staleness
                    # exchange (sharded_gibbs module doc); τ=0 is the
                    # bit-identity cross-check arm.
                    merge_form=merge_form, merge_staleness=merge_staleness,
                    # Sweep-granular resume INSIDE the fit stage: with a
                    # resume_dir, checkpoint at every superstep boundary
                    # (the fit loop's natural host-sync points) so a
                    # tunnel window that dies mid-fit resumes at the
                    # last completed superstep instead of repaying the
                    # whole fit — the single longest atomic device
                    # stage of the ~51-min 1B runs.
                    checkpoint_every=(SUPERSTEP_DEFAULT
                                      if resume_dir is not None else 0))
    fit_ckpt_dir = (pathlib.Path(resume_dir) / "fit_ckpt"
                    if resume_dir is not None else None)
    mesh = make_mesh(dp=n_dev, mp=1)
    model = ShardedGibbsLDA(cfg, corpus.n_vocab, mesh=mesh)
    saved_model = ckpt.load("model") if ckpt is not None else None
    fabric_manifest = None
    if saved_model is not None:
        # A prior session already paid for the fit — the single
        # longest atomic device stage. walls carry ITS cost, not this
        # session's load time, so rates stay honest across sessions.
        theta = saved_model["theta"]
        phi_wk = saved_model["phi_wk"]
        walls["gibbs_fit"] = float(saved_model["wall"])
    elif fit_hosts > 1:
        # r21 multi-host fabric: the fit runs in fit_hosts worker
        # processes under a jax.distributed coordinator, each owning a
        # dp shard of the corpus and its own checkpoint shard. The
        # fabric workdir rides resume_dir so a killed session (or a
        # killed HOST — the fabric absorbs that itself) resumes from
        # the last superstep boundary common to all shards.
        from onix.parallel import hostfabric
        fabric_dir = (pathlib.Path(resume_dir) / "fit_fabric"
                      if resume_dir is not None
                      else tempfile.mkdtemp(prefix="onix-fabric-"))
        fab = hostfabric.run_fit(
            corpus, cfg, fabric_dir, n_hosts=fit_hosts,
            on_death="rebalance" if rebalance else "restart",
            rebalance=rebalance)
        theta, phi_wk = fab["theta"], fab["phi_wk"]
        fabric_manifest = fab["manifest"]
        walls["gibbs_fit"] = time.monotonic() - t
        if ckpt is not None:
            ckpt.save("model", theta=np.asarray(theta),
                      phi_wk=np.asarray(phi_wk),
                      wall=np.float64(walls["gibbs_fit"]))
            ckpt.save("meta", elapsed=np.float64(
                prior_elapsed + time.monotonic() - t_all),
                sessions=np.int64(resumed_sessions + 1))
    else:
        fit = model.fit(corpus, checkpoint_dir=fit_ckpt_dir)
        theta, phi_wk = fit["theta"], fit["phi_wk"]  # host np: synced
        walls["gibbs_fit"] = time.monotonic() - t
        if ckpt is not None:
            ckpt.save("model", theta=np.asarray(theta),
                      phi_wk=np.asarray(phi_wk),
                      wall=np.float64(walls["gibbs_fit"]))
            ckpt.save("meta", elapsed=np.float64(
                prior_elapsed + time.monotonic() - t_all),
                sessions=np.int64(resumed_sessions + 1))

    planted = set(cols["anomaly_idx"].tolist())
    stream_info: dict = {}
    t = time.monotonic()
    if train_events >= n_events:
        # Fused device path: score -> pair-min -> bottom-k in one
        # compiled scan; only the winners cross the tunnel. Words were
        # already built on host for training, so the manifest schema
        # stays uniform with the streaming path's words_mode.
        stream_info["words_mode"] = "host"
        top = select_suspicious_events(bundle, theta, phi_wk, n_events,
                                       tol=1.0, max_results=max_results)
        top_idx = np.asarray(top.indices)
        top_scores = np.asarray(top.scores)
        walls["score_select"] = time.monotonic() - t
    else:
        del cols

        def _save_meta():
            if ckpt is not None:
                ckpt.save("meta", elapsed=np.float64(
                    prior_elapsed + time.monotonic() - t_all),
                    sessions=np.int64(resumed_sessions + 1))

        top_idx, top_scores = _stream_score(
            bundle, wt.edges, theta, phi_wk, n_events=n_events,
            chunk_events=train_events, n_hosts=n_hosts, seed=seed,
            max_results=max_results, planted=planted, walls=walls,
            datatype=datatype, info=stream_info, gen_arrays=gen_arrays,
            ckpt=ckpt, save_meta=_save_meta)

    if resumed_sessions:
        # Resumed runs replay the deterministic CPU stages, so raw
        # elapsed double-counts them; the single-run-equivalent total
        # (each stage's wall counted once — device stages carry the
        # session that actually paid them) is what the rate means.
        # Raw all-session elapsed rides along for transparency.
        walls["wall_all_sessions"] = round(
            prior_elapsed + time.monotonic() - t_all, 2)
        walls["total"] = sum(
            walls.get(k, 0.0) for k in
            ("synthesize", "word_creation", "corpus_build", "gibbs_fit",
             "score_select", "stream_synth", "stream_words_map",
             "stream_score"))
    else:
        walls["total"] = time.monotonic() - t_all
    # The judged rate excludes generating the benchmark's own input —
    # a real deployment reads landed telemetry, it does not synthesize
    # it (VERDICT r2 weak #3 / next #2).
    gen_wall = walls["synthesize"] + walls.get("stream_synth", 0.0)
    walls["generation_total"] = round(gen_wall, 2)
    pipeline_wall = max(walls["total"] - gen_wall, 1e-9)
    hits = len(planted & set(top_idx[top_idx >= 0].tolist()))
    finite = top_scores[np.isfinite(top_scores)]
    cfg_of = {"flow": "configs[3] (synthetic flow day)",
              "dns": "configs[1] at scale (synthetic dns day)",
              "proxy": "configs[2] at scale (synthetic proxy day)"}
    manifest = {
        "config": f"BASELINE {cfg_of[datatype]}",
        "datatype": datatype,
        "n_events": n_events,
        "train_events": train_events,
        "n_hosts": n_hosts,
        "n_docs": int(corpus.n_docs),
        "n_vocab": int(corpus.n_vocab),
        "n_train_tokens": int(corpus.n_tokens),
        "n_topics": n_topics,
        "n_sweeps": n_sweeps,
        "n_chains": n_chains,
        # Fit-loop structure (r7): sweeps per fused dispatch, and
        # whether the dp=1 shard_map bypass was engaged — the two knobs
        # behind the gibbs_fit wall this manifest reports.
        "lda_superstep": cfg.superstep or SUPERSTEP_DEFAULT,
        "dp1_fast_path": bool(getattr(model, "dp1_fast", False)),
        # Orchestration topology stamp (r14): downstream evidence JSONs
        # must be self-describing — which merge arm fitted the model,
        # at what staleness, under which orchestration — instead of the
        # r3-era bare-walls SCALE_1B layout. scale.py is the sequential
        # single-datatype runner (overlap 0); the overlapped
        # three-datatype form is pipelines/campaign.py, which stamps
        # the same block.
        "orchestration": {
            "runner": "scale_sequential",
            "overlap": False,
            "overlap_depth": 0,
            "merge_form": getattr(model, "merge_form", "sync"),
            "merge_staleness": int(getattr(model, "merge_tau", 0)),
            "lda_superstep": cfg.superstep or SUPERSTEP_DEFAULT,
            "dp1_fast_path": bool(getattr(model, "dp1_fast", False)),
            "mesh": dict(mesh.shape),
            # r21 multi-host fabric stamp: how many worker processes
            # fitted the model, and (when the fabric ran this session)
            # its full manifest — deaths, restarts, rebalance, resume
            # sweeps, host.* counters. Absent fields mean the fit was
            # in-process or resumed from a prior session's model.
            "fit_hosts": fit_hosts,
            **({"fit_fabric": fabric_manifest}
               if fabric_manifest is not None else {}),
            "per_datatype_stage_walls_s": {
                datatype: {k: round(v, 2) for k, v in walls.items()}},
        },
        "devices": [str(d) for d in jax.devices()],
        "mesh": dict(mesh.shape),
        "walls_seconds": {k: round(v, 2) for k, v in walls.items()},
        "events_per_second_end_to_end": round(n_events / walls["total"], 1),
        "events_per_second_pipeline_only": round(n_events / pipeline_wall, 1),
        "planted_anomalies": len(planted),
        "planted_in_bottom_k": hits,
        "selected_score_range": ([float(finite.min()), float(finite.max())]
                                 if len(finite) else None),
        "max_results": max_results,
        "seed": seed,
        **({"resumed_sessions": resumed_sessions + 1}
           if resumed_sessions else {}),
        **stream_info,
    }
    # Resilience events this run tallied (retries, salvage skips,
    # injected faults, checkpoint digest mismatches) — empty on a clean
    # run, and the chaos harness's evidence on a faulted one.
    from onix.utils import telemetry
    from onix.utils.obs import counters
    # r18: the telemetry view (span histograms + recorder tallies,
    # zeros included) — every scale manifest says what was observed
    # live, not just what summed post-hoc.
    manifest["telemetry"] = telemetry.snapshot()
    resil = {**counters.snapshot("ingest"), **counters.snapshot("salvage"),
             **counters.snapshot("faults"), **counters.snapshot("ckpt"),
             **counters.snapshot("scale.resume_torn_discarded")}
    if resil:
        manifest["resilience"] = resil
    if out_path is not None:
        out_path = pathlib.Path(out_path)
        out_path.parent.mkdir(parents=True, exist_ok=True)
        out_path.write_text(json.dumps(manifest, indent=2) + "\n")
    return manifest


class _ResumeState:
    """Stage/chunk checkpointing for scale runs on the intermittent
    tunnel (VERDICT r04 next #1: the ~51-min 1B run must survive
    ~40-minute tunnel windows). The design persists only the SMALL
    state — the fitted model (theta/phi, ≤ tens of MB) and each
    completed stream chunk's bottom-k winners (≤ max_results rows) —
    because the big stages before the fit (synthesize → words →
    corpus) are deterministic in `seed` and CPU-only: a resumed run
    replays them without touching the device, loads the model instead
    of re-fitting, and continues streaming at the first chunk that
    never finished. Checkpoints are fingerprinted over every argument
    that changes the numbers; a mismatch starts clean rather than
    resuming somebody else's run."""

    def __init__(self, resume_dir, fingerprint: dict):
        self.dir = pathlib.Path(resume_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.fp = json.dumps(fingerprint, sort_keys=True)
        fp_file = self.dir / "fingerprint.json"
        if fp_file.exists() and fp_file.read_text() != self.fp:
            for p in self.dir.glob("*.npz"):
                p.unlink()
            fp_file.unlink()
        self.fresh = not fp_file.exists()
        if self.fresh:
            fp_file.write_text(self.fp)

    def _path(self, name: str) -> pathlib.Path:
        return self.dir / f"{name}.npz"

    def save(self, name: str, **arrays) -> None:
        tmp = self._path(name).with_suffix(".tmp.npz")
        np.savez(tmp, **arrays)
        os.replace(tmp, self._path(name))

    def load(self, name: str):
        p = self._path(name)
        if not p.exists():
            return None
        try:
            return np.load(p, allow_pickle=False)
        except Exception as e:          # torn write from a killed run
            from onix.utils.obs import counters
            counters.inc("scale.resume_torn_discarded")
            print(f"scale resume: discarding torn checkpoint {p} ({e!r})")
            p.unlink()
            return None


def _default_anomalies(n_events: int) -> int:
    """Sublinear in n: at 10^8+, a linear anomaly count concentrates
    enough repeated signature words that the sampler gives the attack
    its own topic and the events stop being low-probability (the
    planted-anomaly contract assumes heterogeneity)."""
    return max(30, min(1000, n_events // 10_000))


def extend_model_for_unseen(theta, phi_wk):
    """Extend (theta, phi) by one UNSEEN row each for scoring events
    outside the training window: an unseen word scores at HALF the
    rarest seen word's probability in every topic (strictly more
    suspicious than anything seen), an unseen document at the uniform
    prior mixture. Chained estimators ([C, D, K] / [C, V, K] from
    n_chains > 1) extend every chain; score_table downstream combines
    them with the geometric mean exactly as score_events does."""
    theta = np.asarray(theta)
    phi = np.asarray(phi_wk)
    k = theta.shape[-1]
    if theta.ndim == 2:
        theta_x = np.concatenate(
            [theta, np.full((1, k), 1.0 / k, np.float32)])
        phi_x = np.concatenate([phi, phi.min(axis=0, keepdims=True) * 0.5])
        return theta_x, phi_x
    c = theta.shape[0]
    theta_x = np.concatenate(
        [theta, np.full((c, 1, k), 1.0 / k, np.float32)], axis=1)
    phi_x = np.concatenate([phi, phi.min(axis=1, keepdims=True) * 0.5],
                           axis=1)
    return theta_x, phi_x


def _stream_score(bundle, fitted_edges, theta, phi_wk, *, n_events: int,
                  chunk_events: int, n_hosts: int, seed: int,
                  max_results: int, planted: set, walls: dict,
                  datatype: str = "flow", info: dict | None = None,
                  gen_arrays=None, ckpt=None, save_meta=None):
    """Stream the FULL day through the fused device scorer in
    chunk_events-sized pieces against a model fitted on chunk 0.

    Vocabulary and document ids are extended by one UNSEEN row each:
    an unseen word scores at half the rarest seen word's probability
    (strictly more suspicious than anything seen in training); an
    unseen document gets the uniform prior mixture. Per chunk only the
    top-k winners stay on host, so peak memory is one chunk's columns.
    """
    import jax.numpy as jnp

    from onix.models import scoring

    info = {} if info is None else info
    # Direct callers (exp_flow_recall.py and any embedder predating the
    # generator parameter) stream the default mixture synth.
    if gen_arrays is None:
        gen_arrays = SYNTH_ARRAYS
    theta_x, phi_x = extend_model_for_unseen(theta, phi_wk)
    d_x, v_x = theta_x.shape[-2], phi_x.shape[-2]
    chains = theta_x.shape[0] if theta_x.ndim == 3 else 1
    # Chain-aware budget (same form as score_all's gate): the geometric
    # merge materializes a [C, D, V] per-chain array before reducing.
    if chains * d_x * v_x > scoring.TABLE_MAX_ELEMS:
        raise ValueError(
            f"extended score table {chains}x{d_x}x{v_x} exceeds the "
            f"device budget; lower n_hosts/n_chains or shard the table")
    table = scoring.score_table(jnp.asarray(theta_x),
                                jnp.asarray(phi_x)).ravel()
    # One bf16 copy for the whole stream — the screened scan would
    # otherwise re-convert the (up to 512 MB) table every batch.
    table_b = (table.astype(jnp.bfloat16)
               if scoring._screened_enabled() else None)

    unseen_w = v_x - 1
    unseen_d = d_x - 1
    # On-device word creation — the DEFAULT hot path for all three
    # datatypes: the raw numeric/dictionary columns ship to the chip
    # and ONE fused program does binning→packing→trained-id
    # lookup→score→bottom-k — stream_words_map collapses into
    # stream_score (string features stay host-side per UNIQUE value for
    # dns/proxy). The host builders remain behind ONIX_HOST_WORDS=1 as
    # the cross-check arm; device_words.py documents the f32 bin-edge
    # caveat and the compact-key range gates (a trained vocab outside
    # the ranges raises at table build → host path, announced).
    device_words = not host_words_forced()
    # Flow tables are built lazily from the FIRST streamed chunk, whose
    # cols["proto_classes"] is the caller proto-id order the device
    # remap must key on (the fitted table is sorted — a different
    # beast; build_flow_tables' contract).
    dev_tables = None
    walls.setdefault("stream_words_map", 0.0)
    if device_words and datatype != "flow":
        from onix.pipelines import device_words as dw
        # Timed into stream_words_map like the flow build: the O(V+D)
        # re-encode is pipeline work, identical accounting across
        # datatypes.
        t_build = time.monotonic()
        try:
            dev_tables = (dw.build_dns_tables(bundle, fitted_edges)
                          if datatype == "dns"
                          else dw.build_proxy_tables(bundle, fitted_edges))
        except ValueError as e:
            print(f"device words unavailable ({e}); using the host path")
            device_words = False
        walls["stream_words_map"] += time.monotonic() - t_build
    info["words_mode"] = "device" if device_words else "host"
    # Streamed chunks plant a day-proportional share of anomalies, not
    # a full day's worth per chunk: the streamed part of the run plants
    # ~one _default_anomalies(n_events) budget, so planted_in_bottom_k
    # is read against max_results rather than being diluted by
    # n_chunks x more planted events than result slots.
    n_chunks = -(-n_events // chunk_events)
    anomalies_per_chunk = max(1, _default_anomalies(n_events) // n_chunks)
    all_scores: list[np.ndarray] = []
    all_idx: list[np.ndarray] = []
    # Generation is NOT the pipeline: r2's 1B artifact spent 64% of its
    # wall synthesizing its own input and the headline conflated the
    # two (VERDICT weak #3). stream_synth times the generator alone;
    # stream_words_map is the real pipeline work (word creation +
    # trained-id mapping) and joins the pipeline-only rate.
    walls["stream_synth"] = 0.0
    walls["stream_score"] = 0.0
    offset = 0
    c = 0
    prog = ckpt.load("stream") if ckpt is not None else None
    if prog is not None:
        # Resume at the first chunk that never completed: restore the
        # winners-so-far, the planted ids streamed chunks added, and
        # the stream walls the prior sessions already paid.
        c = int(prog["c"])
        offset = min(c * chunk_events, n_events)
        all_idx.append(prog["idx"].astype(np.int64))
        all_scores.append(prog["scores"].astype(np.float32))
        planted.update(prog["planted"].tolist())
        for k in ("stream_synth", "stream_words_map", "stream_score"):
            walls[k] += float(prog[f"wall_{k}"])
        info["resumed_at_chunk"] = c

    def _save_progress():
        if ckpt is None:
            return
        ckpt.save(
            "stream", c=np.int64(c),
            idx=(np.concatenate(all_idx) if all_idx
                 else np.zeros(0, np.int64)),
            scores=(np.concatenate(all_scores) if all_scores
                    else np.zeros(0, np.float32)),
            planted=np.asarray(sorted(planted), np.int64),
            **{f"wall_{k}": np.float64(walls[k]) for k in
               ("stream_synth", "stream_words_map", "stream_score")})
        if save_meta is not None:
            save_meta()

    if device_words:
        from onix.pipelines import device_words as dw

    def _synth_chunk(ci: int, mi: int) -> dict:
        t0 = time.monotonic()
        cc = gen_arrays[datatype](mi, n_hosts=n_hosts,
                                  n_anomalies=anomalies_per_chunk,
                                  seed=seed + 1000 * ci)
        walls["stream_synth"] += time.monotonic() - t0
        return cc

    def _stage_cols(cc: dict):
        """START one synthesized chunk's host→device transfer
        (device_put returns with the copy in flight — device_words
        staging block comment). Raises ValueError when the trained
        bundle cannot ride the compact keys (flow table build gates)."""
        nonlocal dev_tables
        t0 = time.monotonic()
        if dev_tables is None:      # flow: keyed on the caller proto order
            dev_tables = dw.build_flow_tables(
                bundle, fitted_edges, list(cc["proto_classes"]))
        staged = dw.STAGE_FNS[datatype](cc, fitted_edges)
        walls["stream_words_map"] += time.monotonic() - t0
        return staged

    def _stage_chunk(ci: int, mi: int):
        """Synthesize chunk ci and stage it. Returns (staged cols,
        planted event ids); the planted merge is deferred until the
        chunk actually processes so a resume never inherits plants
        from a chunk that was only ever prefetched."""
        cc = _synth_chunk(ci, mi)
        return _stage_cols(cc), set((cc["anomaly_idx"] + ci * chunk_events)
                                    .tolist())

    def _host_idx(cols: dict) -> np.ndarray:
        """Host mapping: the reference word builders + searchsorted id
        maps into the TRAINED id spaces; unknowns go to the UNSEEN
        rows. No per-chunk unique sort: at 2x10^8 tokens/chunk the old
        unique-then-map path spent most of the 1B run's wall in these
        sorts (docs/SCALE_1B_r02.json)."""
        t0 = time.monotonic()
        wt = _words_from_cols(datatype, cols, edges=fitted_edges)
        wid = bundle.word_ids_packed(wt.word_key, fill=unseen_w)
        did = bundle.doc_ids_u32(wt.ip_u32, fill=unseen_d)
        out = did * np.int32(v_x) + wid
        walls["stream_words_map"] += time.monotonic() - t0
        return out

    def _fused_bottom_k(staged):
        if datatype == "flow":
            return dw.flow_stream_bottom_k(
                dev_tables, table, staged, v_x=v_x, unseen_w=unseen_w,
                unseen_d=unseen_d, tol=1.0, max_results=max_results)
        if datatype == "dns":
            return dw.dns_stream_bottom_k(
                dev_tables, table, staged, fitted_edges, v_x=v_x,
                unseen_w=unseen_w, unseen_d=unseen_d, tol=1.0,
                max_results=max_results)
        return dw.proxy_stream_bottom_k(
            dev_tables, table, staged, fitted_edges, v_x=v_x,
            unseen_w=unseen_w, unseen_d=unseen_d, tol=1.0,
            max_results=max_results)

    prefetched = None      # (chunk index, staged cols, planted ids)
    while offset < n_events:
        m = min(chunk_events, n_events - offset)
        top = None         # set by the fused device arm only
        if c == 0:
            # Chunk 0 is the training window — its corpus is already
            # mapped; reuse the integer ids directly.
            # int32 throughout: the extended table is capped at 2^27
            # elements, so every flat index fits with room to spare —
            # int64 temporaries would double the chunk's memory.
            t = time.monotonic()
            d_ids = bundle.corpus.doc_ids[:bundle.n_real_tokens]
            w_ids = bundle.corpus.word_ids[:bundle.n_real_tokens]
            idx = (d_ids.astype(np.int32) * np.int32(v_x)
                   + w_ids.astype(np.int32))
            walls["stream_words_map"] += time.monotonic() - t
        elif device_words:
            # Double-buffered device path: the raw columns ARE the
            # input — words+map+score+select run as one fused program
            # inside stream_score; stream_words_map holds only the
            # once-per-run O(V+D) table re-encode plus per-chunk
            # staging casts. While THIS chunk's scan occupies the
            # device, the NEXT chunk is synthesized and its transfer
            # started, so H2D copy overlaps compute instead of
            # serializing with it.
            staged = None
            if prefetched is not None and prefetched[0] == c:
                staged, planted_c = prefetched[1], prefetched[2]
            else:                      # first streamed chunk / resume
                cc = _synth_chunk(c, m)
                planted_c = set((cc["anomaly_idx"] + c * chunk_events)
                                .tolist())
                try:
                    staged = _stage_cols(cc)
                except ValueError as e:
                    # Same degrade rule as the dns/proxy upfront table
                    # build: a trained vocabulary outside the compact-
                    # key ranges rides the host path for the rest of
                    # the run, announced — the default path degrades,
                    # it does not crash mid-stream.
                    print(f"device words unavailable ({e}); "
                          "using the host path")
                    device_words = False
                    info["words_mode"] = "host"
                    idx = _host_idx(cc)
                del cc
            prefetched = None
            planted.update(planted_c)
            if staged is not None:
                t = time.monotonic()
                top = _fused_bottom_k(staged)     # async dispatch
                walls["stream_score"] += time.monotonic() - t
                del staged
                if offset + m < n_events:
                    prefetched = (c + 1, *_stage_chunk(
                        c + 1, min(chunk_events, n_events - offset - m)))
                idx = None
        else:
            # Host cross-check arm (ONIX_HOST_WORDS=1): the reference
            # word builders + searchsorted id maps.
            cols = _synth_chunk(c, m)
            planted.update((cols["anomaly_idx"] + offset).tolist())
            idx = _host_idx(cols)
            del cols

        t = time.monotonic()
        if top is None:
            if datatype == "flow":  # [src|dst] halves: fused pair-min path
                top = scoring.table_pair_bottom_k_fast(
                    table, jnp.asarray(idx[:m]), jnp.asarray(idx[m:]),
                    table_b, tol=1.0, max_results=max_results)
            else:                   # one client-IP token per event
                top = scoring.table_bottom_k_fast(
                    table, jnp.asarray(idx), table_b,
                    tol=1.0, max_results=max_results)
            idx = None
        ti = np.asarray(top.indices)       # blocks on the fused scan
        ts = np.asarray(top.scores)
        keep = ti >= 0
        all_idx.append(ti[keep] + offset)
        all_scores.append(ts[keep])
        walls["stream_score"] += time.monotonic() - t
        offset += m
        c += 1
        _save_progress()

    scores = np.concatenate(all_scores)
    idxs = np.concatenate(all_idx)
    order = np.argsort(scores, kind="stable")[:max_results]
    out_idx = np.full(max_results, -1, np.int64)
    out_scores = np.full(max_results, np.inf, np.float32)
    out_idx[:len(order)] = idxs[order]
    out_scores[:len(order)] = scores[order]
    return out_idx, out_scores


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="onix scale demo — end-to-end synthetic telemetry day")
    ap.add_argument("--datatype", choices=("flow", "dns", "proxy"),
                    default="flow")
    ap.add_argument("--events", type=float, default=1e8)
    ap.add_argument("--hosts", type=int, default=None)
    ap.add_argument("--sweeps", type=int, default=20)
    ap.add_argument("--train-events", type=float, default=None,
                    help="fit on this many events, stream-score the rest "
                         "(default: train on everything)")
    ap.add_argument("--out", default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--chains", type=int, default=1,
                    help="restart-ensemble chains on the sharded "
                         "engine (the judged-overlap estimator)")
    ap.add_argument("--generator", choices=("mixture", "sessions"),
                    default="mixture",
                    help="telemetry generator: the round-1 role-mixture "
                         "synth, or the independent session/state-"
                         "machine generator (synth2)")
    ap.add_argument("--resume-dir", default=None,
                    help="stage/chunk checkpoint dir: a run killed "
                         "mid-way (severed TPU tunnel window) resumes "
                         "from the last completed stage / stream chunk "
                         "instead of restarting")
    ap.add_argument("--merge-form", choices=("sync", "async"),
                    default="sync",
                    help="sharded-engine count-merge arm (r14): sync "
                         "full-barrier psum fold, or the AD-LDA-style "
                         "bounded-staleness exchange")
    ap.add_argument("--merge-staleness", type=int, default=1,
                    help="merge windows a peer delta may lag in the "
                         "async arm (0 = the bit-identity arm)")
    ap.add_argument("--fit-hosts", type=int, default=1,
                    help="fit worker PROCESSES in the r21 multi-host "
                         "fabric (parallel/hostfabric.py); 1 = the "
                         "in-process sharded engine. Distinct from "
                         "--hosts, which is the SYNTHETIC telemetry "
                         "host population")
    ap.add_argument("--rebalance", action="store_true",
                    help="multi-host fabric only: when a fit host dies, "
                         "re-shard its corpus onto the survivors behind "
                         "a deliberate fingerprint bump instead of "
                         "restarting the same topology")
    args = ap.parse_args(argv)
    m = run_scale(int(args.events), n_hosts=args.hosts,
                  n_sweeps=args.sweeps, seed=args.seed,
                  train_events=(None if args.train_events is None
                                else int(args.train_events)),
                  datatype=args.datatype, n_chains=args.chains,
                  resume_dir=args.resume_dir, generator=args.generator,
                  merge_form=args.merge_form,
                  merge_staleness=args.merge_staleness,
                  fit_hosts=args.fit_hosts, rebalance=args.rebalance,
                  out_path=args.out)
    print(json.dumps(m, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
