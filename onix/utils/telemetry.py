"""End-to-end telemetry: request-scoped spans, log-bucketed histograms,
Prometheus exposition, and the chaos flight recorder.

The pre-r18 observability stack is post-hoc only: `obs.CounterRegistry`
counts events, `obs.OccupancyClock` sums stage walls, and serving
quantiles were computed from ad-hoc latency lists after a harness run
ended. Nothing answered the live operator questions — "why was THIS
request slow", "what are the current p50/p99 per degradation rung", or
"what happened in the seconds before that fault fired". This module is
the live layer, four pieces sharing one discipline (near-zero cost when
off, no device-program changes ever — telemetry off is asserted
bit-identical in tier-1, tests/test_telemetry.py):

* **Spans** (`Tracer`) — monotonic-clock spans carrying a `trace_id`
  propagated through `contextvars` end-to-end: HTTP `X-Request-Id` on
  `/score` → `BankService.submit` → admission queue wait → bank wave
  dispatch; campaign stages and streaming batches get per-item trace
  ids. The hot path is LOCK-FREE: a disabled or sampled-out span takes
  no lock and allocates nothing beyond the context manager; a recorded
  span-close pays one ring append (GIL-atomic `deque.append`) plus the
  histogram observe. Spans FEED `OccupancyClock` accounting when given
  a clock (`span(..., clock=, clock_name=)` enters `clock.busy`
  unconditionally — occupancy numbers never depend on telemetry being
  on) instead of duplicating it. Literal span names are a declared
  contract: `SPAN_REGISTRY` below, machine-checked by the `spans`
  analysis pass (python -m onix.analysis) exactly like counter
  namespaces and env vars.

* **Histograms** (`Histogram`, `HistogramRegistry`) — log-bucketed
  (geometric buckets, growth `Histogram.GROWTH`): `observe(v)` lands v
  in bucket ⌈log_g v⌉, so any quantile read back is exact-to-the-bucket
  with a KNOWN relative error bound (`rel_error` = √g − 1, ~9% at the
  default g = 2^(1/4)). Every closed span observes its duration into
  the process registry under ``span.<name>`` (seconds), which is what
  `/metrics` renders and what replaced the ad-hoc quantile lists in
  `serving/load_harness.py` (parity-tested against numpy percentile).

* **Exposition** — `render_prometheus` writes the Prometheus text
  format (counters, histograms with cumulative `le` buckets, gauges,
  an info metric); `parse_prometheus_text` is the strict in-tree
  parser the tests and scripts/lint.sh check the output with, so the
  exposition can never drift into something a real scraper rejects.
  `GET /metrics` on `onix serve` (oa/serve.py) is the live endpoint.

* **Flight recorder** (`FlightRecorder`) — a bounded ring of recent
  span-close / counter-delta / fault events (counter deltas arrive via
  the observer hook this module installs on `obs.counters` at import).
  `dump(reason)` writes the ring + a full counter snapshot to a JSON
  artifact; the wired triggers are: any fault-plan site firing
  (faults.fire), a request shedding (BankService.submit), a model
  digest mismatch refusing (checkpoint.py), and a `faults`-marker test
  failing (tests/conftest.py) — so every chaos failure carries its own
  postmortem. Dumps only land when a directory is routed (config
  `telemetry.recorder_dir`, applied by `apply_config`, or the
  ONIX_TELEMETRY_DIR env fallback); an unrouted dump is counted
  (`telemetry.recorder_dump_unrouted`), never written into cwd.

Kill switches: config `telemetry.enabled=false` / `telemetry.sample=0`
(durable), ONIX_TELEMETRY=0 (env override for drills). Off means: no
spans recorded, no ring events, no histogram observations, no dumps —
and bit-identical winners with unchanged per-program dispatch counts,
asserted (the hard constraint this layer ships under).

docs/OBSERVABILITY.md is the operator page for all four pieces.
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import contextvars
import dataclasses
import itertools
import json
import math
import os
import pathlib
import re
import threading
import time
import zlib

from onix.utils.obs import counters

#: Declared span names: the first argument of every literal
#: `TRACER.span(...)`/`TRACER.observe(...)` call must be a key here —
#: machine-checked by `python -m onix.analysis` (the `spans` pass),
#: because a typo'd span name is a latency series that silently never
#: aggregates with its siblings. Dead declarations (declared, never
#: opened) are findings too. Renders into docs/ROBUSTNESS.md
#: (generated section `span-registry`).
SPAN_REGISTRY: dict[str, str] = {
    "bank.admit": "ModelBank._ensure_resident: one wave's residency admission (LRU + H2D staging)",
    "bank.prefetch": "ModelBank.prefetch: one bulk host-tier promotion pass (Zipf-predicted tenants, disk -> host RAM)",
    "bank.score_wave": "one batched bank dispatch: kernel call + winner fetch for one wave (single-device path)",
    "bank.wave": "sharded bank: one per-device wave's admission + async program launch (fetch drains later)",
    "campaign.fit": "campaign orchestrator: one datatype's device fit (retries included)",
    "campaign.oa": "campaign orchestrator: one datatype's OA stage",
    "campaign.prepare": "campaign orchestrator: one datatype's host prepare (synth -> words -> corpus)",
    "campaign.score": "campaign orchestrator: one datatype's scoring stage",
    "daily.day": "daily supervisor: one simulated day end-to-end (campaign + model save + ledger write)",
    "daily.refit": "daily supervisor: one datatype's warm/cold refit decision — warm fit, drift check, and any drift-forced cold refit",
    "fleet.day": "fleet supervisor: one simulated day across every executing tenant (prepare, fleet refit, per-tenant accepts)",
    "fleet.refit": "fleet supervisor: the day's fused fleet refit — stacked warm/cold class dispatches plus the drift-gated cold second pass",
    "host.fit": "hostfabric coordinator: one multi-host fit end-to-end (spawn, monitor, deaths + restarts, result assembly)",
    "host.superstep": "hostfabric worker: one fused superstep segment dispatch, collective deadline + retry wrapper included",
    "serve.queue_wait": "BankService.submit: admitted-to-scoring-start wall (the admission queue wait)",
    "serve.request": "oa/serve.py /score: one HTTP request, receipt to response",
    "serve.score": "BankService.score body: cache lookups + bank dispatch for one batch",
    "serve.submit": "BankService.submit: one admitted request batch, queue wait + scoring",
    "stream.batch": "StreamingScorer.process: one streaming minibatch end-to-end",
    "stream.superstep": "StreamingScorer: one fused S-batch superstep dispatch",
}

# ---------------------------------------------------------------------------
# Histograms.
# ---------------------------------------------------------------------------


class Histogram:
    """Log-bucketed histogram: bucket i covers (g^(i-1), g^i], values
    <= 0 land in a dedicated underflow bucket with upper edge 0. A
    quantile read returns the geometric midpoint of its bucket, so the
    true quantile lies within the bucket's edges — `quantile_bounds`
    returns them, and `rel_error` (= sqrt(g) - 1) bounds the midpoint's
    relative error. Exact-to-the-bucket by construction: no sampling,
    no decay, every observation counted. Thread-safe."""

    GROWTH = 2 ** 0.25          # ~19% bucket width, ~9% midpoint error
    _UNDERFLOW = -(10 ** 9)     # bucket index for values <= 0

    #: Lock discipline, machine-checked by the `locks` analysis pass.
    GUARDED_BY = {"_counts": "_lock", "n": "_lock", "sum": "_lock",
                  "min": "_lock", "max": "_lock"}

    def __init__(self, growth: float | None = None):
        self.growth = float(growth if growth is not None else self.GROWTH)
        if self.growth <= 1.0:
            raise ValueError("histogram growth must be > 1")
        self._log_g = math.log(self.growth)
        self._lock = threading.Lock()
        self._counts: dict[int, int] = {}
        self.n = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    @property
    def rel_error(self) -> float:
        """Worst-case relative error of `quantile`'s midpoint answer."""
        return math.sqrt(self.growth) - 1.0

    def _bucket(self, value: float) -> int:
        if value <= 0.0:
            return self._UNDERFLOW
        # ceil(log_g v): the smallest i with g^i >= v.
        return math.ceil(math.log(value) / self._log_g - 1e-12)

    def edge(self, bucket: int) -> float:
        """Upper edge of a bucket (0.0 for the underflow bucket)."""
        return 0.0 if bucket == self._UNDERFLOW else self.growth ** bucket

    def observe(self, value: float) -> None:
        b = self._bucket(float(value))
        with self._lock:
            self._counts[b] = self._counts.get(b, 0) + 1
            self.n += 1
            self.sum += float(value)
            if value < self.min:
                self.min = float(value)
            if value > self.max:
                self.max = float(value)

    def _sorted_counts(self) -> list[tuple[int, int]]:
        with self._lock:
            return sorted(self._counts.items())

    def quantile_bounds(self, q: float) -> tuple[float, float]:
        """(lower edge, upper edge) of the bucket holding the q-quantile
        (nearest-rank): the true quantile of the observed values lies in
        this closed interval. (0.0, 0.0) on an empty histogram."""
        items = self._sorted_counts()
        total = sum(c for _, c in items)
        if total == 0:
            return 0.0, 0.0
        # rank <= total for q <= 1, so the loop always returns; clamp
        # out-of-range q instead of walking past the last bucket.
        rank = min(max(1, math.ceil(q * total)), total)
        seen = 0
        for b, c in items:
            seen += c
            if seen >= rank:
                if b == self._UNDERFLOW:
                    return 0.0, 0.0
                return self.growth ** (b - 1), self.growth ** b
        raise AssertionError("unreachable: rank clamped to total")

    def quantile(self, q: float) -> float:
        """Geometric bucket midpoint of the q-quantile; within
        `rel_error` of the true nearest-rank quantile, clamped into the
        observed [min, max] so tiny samples don't report an edge no
        observation reached."""
        lo, hi = self.quantile_bounds(q)
        if hi == 0.0:
            return 0.0
        mid = math.sqrt(lo * hi)
        if self.n:
            mid = min(max(mid, self.min), self.max)
        return mid

    def snapshot(self) -> dict:
        """Manifest-ready summary: count/sum/min/max, the three judged
        quantiles, the error bound, and the (sparse) bucket table as
        [upper_edge, count] rows."""
        items = self._sorted_counts()
        with self._lock:
            n, s = self.n, self.sum
            mn = self.min if self.n else None
            mx = self.max if self.n else None
        return {
            "n": n,
            "sum": round(s, 9),
            "min": mn, "max": mx,
            "p50": self.quantile(0.50),
            "p99": self.quantile(0.99),
            "p999": self.quantile(0.999),
            "rel_error": round(self.rel_error, 4),
            "buckets": [[self.edge(b), c] for b, c in items],
        }


class HistogramRegistry:
    """Process-wide named histograms — the distribution analog of
    `obs.CounterRegistry` (dotted names, same prefix-snapshot
    discipline). `observe` is the one hot call: the per-name lookup
    rides a plain dict read (GIL-atomic); only histogram CREATION takes
    the registry lock."""

    #: Lock discipline, machine-checked by the `locks` analysis pass.
    GUARDED_BY = {"_hists": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._hists: dict[str, Histogram] = {}

    def observe(self, name: str, value: float) -> None:
        h = self._hists.get(name)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(name, Histogram())
        h.observe(value)

    def get(self, name: str) -> Histogram | None:
        return self._hists.get(name)

    def names(self, prefix: str = "") -> list[str]:
        return sorted(k for k in self._hists if k.startswith(prefix))

    def snapshot(self, prefix: str = "", buckets: bool = False) -> dict:
        """name -> histogram summary (bucket tables only on request —
        manifests want quantiles, not 200 rows per series)."""
        out = {}
        for name in self.names(prefix):
            h = self._hists.get(name)
            if h is None:
                continue
            snap = h.snapshot()
            if not buckets:
                snap.pop("buckets")
            out[name] = snap
        return out

    def reset(self, prefix: str = "") -> None:
        with self._lock:
            if not prefix:
                self._hists.clear()
            else:
                for k in [k for k in self._hists if k.startswith(prefix)]:
                    del self._hists[k]


#: The process-global histogram registry (tests reset() it).
histograms = HistogramRegistry()


# ---------------------------------------------------------------------------
# Flight recorder.
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded ring of recent telemetry events (span closes, counter
    deltas, fault firings). `record` is lock-free — `deque.append` with
    a maxlen is GIL-atomic, and losing strict ordering between racing
    threads is acceptable for a postmortem buffer (each event carries
    its own monotonic stamp). `dump` snapshots the ring plus a full
    counter snapshot into a JSON artifact; dumps are capped per process
    (`max_dumps`) so a fault storm cannot fill a disk, and are counted
    either way (`telemetry.recorder_dumps` /
    `telemetry.recorder_dump_skipped` / `..._unrouted`)."""

    #: Dump bookkeeping is the only locked state; the ring itself is
    #: deliberately lock-free (see class docstring).
    GUARDED_BY = {"_dumps": "_dump_lock"}

    def __init__(self, capacity: int = 1024, out_dir=None,
                 max_dumps: int = 32):
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self.out_dir = pathlib.Path(out_dir) if out_dir else None
        self.max_dumps = max_dumps
        self._dump_lock = threading.Lock()
        self._dumps = 0

    def reconfigure(self, capacity: int | None = None,
                    out_dir=None) -> None:
        if capacity is not None and capacity != self._ring.maxlen:
            self._ring = collections.deque(self._ring, maxlen=capacity)
        if out_dir is not None:
            self.out_dir = pathlib.Path(out_dir)

    def record(self, kind: str, **fields) -> None:
        self._ring.append({"mono": round(time.perf_counter(), 6),
                           "t": round(time.time(), 3),
                           "kind": kind, **fields})

    def events(self) -> list[dict]:
        return list(self._ring)

    def clear(self) -> None:
        self._ring.clear()
        with self._dump_lock:
            self._dumps = 0

    def _resolve_dir(self) -> pathlib.Path | None:
        if self.out_dir is not None:
            return self.out_dir
        env = os.environ.get("ONIX_TELEMETRY_DIR")
        return pathlib.Path(env) if env else None

    def dump(self, reason: str, extra: dict | None = None):
        """Write the ring to `<dir>/flight-<pid>-<seq>-<reason>.json`.
        Returns the path, or None when unrouted (no dir configured),
        capped out, or telemetry is off — all counted, never silent."""
        if not TRACER.enabled:
            return None
        out_dir = self._resolve_dir()
        if out_dir is None:
            counters.inc("telemetry.recorder_dump_unrouted")
            return None
        with self._dump_lock:
            if self._dumps >= self.max_dumps:
                counters.inc("telemetry.recorder_dump_skipped")
                return None
            self._dumps += 1
            seq = self._dumps
        slug = re.sub(r"[^A-Za-z0-9._-]+", "-", reason)[:80] or "dump"
        path = out_dir / f"flight-{os.getpid()}-{seq:03d}-{slug}.json"
        doc = {
            "reason": reason,
            "pid": os.getpid(),
            "t": round(time.time(), 3),
            "counters": counters.snapshot(),
            "events": self.events(),
        }
        if extra:
            doc["extra"] = extra
        # Everything filesystem-shaped stays inside the except: an
        # unwritable recorder dir must degrade to a counted skip, never
        # leak an OSError into the TRIGGERING path's control flow (a
        # shed would 500 instead of 503, an injected fault would raise
        # the wrong class past its bounded retry).
        try:
            out_dir.mkdir(parents=True, exist_ok=True)
            path.write_text(json.dumps(doc, indent=2, default=repr) + "\n")
        except OSError:
            counters.inc("telemetry.recorder_dump_failed")
            return None
        counters.inc("telemetry.recorder_dumps")
        return path


# ---------------------------------------------------------------------------
# Spans.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SpanRecord:
    """One closed span (what the ring and `Tracer.spans()` hold)."""
    name: str
    trace_id: str
    span_id: int
    parent_id: int | None
    t0: float               # perf_counter at open
    dur_s: float
    error: str | None = None
    attrs: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class _TraceCtx:
    trace_id: str
    sampled: bool


_TRACE: contextvars.ContextVar[_TraceCtx | None] = \
    contextvars.ContextVar("onix_trace", default=None)
_PARENT: contextvars.ContextVar[int | None] = \
    contextvars.ContextVar("onix_span", default=None)

_trace_seq = itertools.count(1)
_span_seq = itertools.count(1)


def new_trace_id() -> str:
    """Process-unique, human-sortable trace id (no host RNG: the id
    stream is deterministic per process, which keeps replays and tests
    reproducible)."""
    return f"t{os.getpid():x}-{next(_trace_seq):08d}"


def current_trace_id() -> str | None:
    ctx = _TRACE.get()
    return ctx.trace_id if ctx is not None else None


class Tracer:
    """The span collector. `enabled=False` or `sample=0.0` turns every
    span into a context manager that only runs its optional clock —
    the lock-free hot path (no ring append, no histogram observe, no
    counter inc). Sampling is deterministic per trace id (crc32 hash),
    so one request's spans are all kept or all dropped together."""

    def __init__(self, enabled: bool = True, sample: float = 1.0):
        self.enabled = enabled and os.environ.get("ONIX_TELEMETRY",
                                                  "1") != "0"
        self.sample = float(sample)

    def configure(self, enabled: bool | None = None,
                  sample: float | None = None) -> None:
        if enabled is not None:
            self.enabled = bool(enabled) \
                and os.environ.get("ONIX_TELEMETRY", "1") != "0"
        if sample is not None:
            if not 0.0 <= sample <= 1.0:
                raise ValueError("telemetry sample must be in [0, 1]")
            self.sample = float(sample)

    def _sampled(self, trace_id: str) -> bool:
        if not self.enabled or self.sample <= 0.0:
            return False
        if self.sample >= 1.0:
            return True
        return (zlib.crc32(trace_id.encode()) & 0xFFFFFFFF) \
            < self.sample * 2 ** 32

    @contextlib.contextmanager
    def trace(self, trace_id: str | None = None):
        """Open a trace scope on the current context (thread/task):
        spans inside share the id and the sampling decision. Yields the
        trace id (the one to echo in X-Request-Id responses)."""
        tid = trace_id or new_trace_id()
        tok = _TRACE.set(_TraceCtx(tid, self._sampled(tid)))
        try:
            yield tid
        finally:
            _TRACE.reset(tok)

    @contextlib.contextmanager
    def span(self, name: str, *, clock=None, clock_name: str | None = None,
             **attrs):
        """One named span. `clock`/`clock_name` FEED an
        `obs.OccupancyClock` busy scope — entered unconditionally, so
        occupancy accounting is identical with telemetry off (the
        feeding-not-duplicating contract). A span that exits via an
        exception is recorded with `error` set and re-raises."""
        ctx = _TRACE.get()
        if ctx is None:
            # Root span with no surrounding trace (direct harness /
            # library calls): open an implicit per-span trace so child
            # spans still nest under one id.
            tid = new_trace_id()
            ctx = _TraceCtx(tid, self._sampled(tid))
            trace_tok = _TRACE.set(ctx)
        else:
            trace_tok = None
        clock_cm = (clock.busy(clock_name or name)
                    if clock is not None else None)
        if clock_cm is not None:
            clock_cm.__enter__()
        if not ctx.sampled:
            try:
                yield None
            finally:
                if clock_cm is not None:
                    clock_cm.__exit__(None, None, None)
                if trace_tok is not None:
                    _TRACE.reset(trace_tok)
            return
        span_id = next(_span_seq)
        parent_id = _PARENT.get()       # the span current BEFORE this one
        parent_tok = _PARENT.set(span_id)
        rec = SpanRecord(name=name, trace_id=ctx.trace_id, span_id=span_id,
                         parent_id=parent_id, t0=time.perf_counter(),
                         dur_s=0.0, attrs=attrs)
        err: str | None = None
        try:
            yield rec
        except BaseException as e:
            err = repr(e)
            raise
        finally:
            _PARENT.reset(parent_tok)
            rec.dur_s = time.perf_counter() - rec.t0
            rec.error = err
            self._close(rec)
            if clock_cm is not None:
                clock_cm.__exit__(None, None, None)
            if trace_tok is not None:
                _TRACE.reset(trace_tok)

    def observe(self, name: str, dur_s: float, **attrs) -> None:
        """Synthesize a closed span of known duration (a wall measured
        inline, e.g. the admission queue wait) — same ring + histogram
        path as `span`, without restructuring the measured code."""
        ctx = _TRACE.get()
        if ctx is None or not ctx.sampled:
            return
        self._close(SpanRecord(
            name=name, trace_id=ctx.trace_id, span_id=next(_span_seq),
            parent_id=_PARENT.get(None), t0=time.perf_counter() - dur_s,
            dur_s=dur_s, attrs=attrs))

    def _close(self, rec: SpanRecord) -> None:
        RECORDER.record("span", name=rec.name, trace_id=rec.trace_id,
                        span_id=rec.span_id, parent_id=rec.parent_id,
                        dur_s=round(rec.dur_s, 6), error=rec.error,
                        **rec.attrs)
        histograms.observe(f"span.{rec.name}", rec.dur_s)
        counters.inc("telemetry.spans_recorded")

    def spans(self, trace_id: str | None = None) -> list[SpanRecord]:
        """Recently closed spans (from the flight ring), optionally for
        one trace — what the end-to-end propagation tests assert on."""
        out = []
        for ev in RECORDER.events():
            if ev.get("kind") != "span":
                continue
            if trace_id is not None and ev.get("trace_id") != trace_id:
                continue
            out.append(SpanRecord(
                name=ev["name"], trace_id=ev["trace_id"],
                span_id=ev["span_id"], parent_id=ev.get("parent_id"),
                t0=0.0, dur_s=ev["dur_s"], error=ev.get("error"),
                attrs={k: v for k, v in ev.items()
                       if k not in ("mono", "t", "kind", "name", "trace_id",
                                    "span_id", "parent_id", "dur_s",
                                    "error")}))
        return out


#: Process-global singletons. `apply_config` (or `configure`) retunes
#: them; tests use `reset_for_tests`.
TRACER = Tracer()
RECORDER = FlightRecorder()


def configure(enabled: bool | None = None, sample: float | None = None,
              recorder_dir=None, recorder_events: int | None = None) -> None:
    TRACER.configure(enabled=enabled, sample=sample)
    RECORDER.reconfigure(capacity=recorder_events, out_dir=recorder_dir)


def apply_config(tcfg) -> None:
    """Apply a `config.TelemetryConfig` (serve and the CLI entry points
    call this once the resolved config exists)."""
    configure(enabled=tcfg.enabled, sample=tcfg.sample,
              recorder_dir=tcfg.recorder_dir or None,
              recorder_events=tcfg.recorder_events)


def reset_for_tests() -> None:
    """Clear the ring, the histogram registry, and the telemetry
    counters; re-enable with full sampling. Tests only."""
    RECORDER.clear()
    RECORDER.out_dir = None
    histograms.reset()
    counters.reset("telemetry")
    TRACER.configure(enabled=True, sample=1.0)


def snapshot(full: bool = False) -> dict:
    """The manifest telemetry block: enablement, span/dump tallies, and
    per-histogram quantile summaries (zeros included — an artifact that
    recorded nothing says so explicitly). `full=True` adds the complete
    counter snapshot and bucket tables (the TPU-queue per-entry
    evidence record)."""
    out = {
        "enabled": TRACER.enabled,
        "sample": TRACER.sample,
        "spans_recorded": counters.get("telemetry.spans_recorded"),
        "recorder_dumps": counters.get("telemetry.recorder_dumps"),
        "recorder_dumps_unrouted":
            counters.get("telemetry.recorder_dump_unrouted"),
        "histograms": histograms.snapshot(buckets=full),
    }
    if full:
        out["counters"] = counters.snapshot()
    return out


# ---------------------------------------------------------------------------
# Prometheus exposition + the strict in-tree parser.
# ---------------------------------------------------------------------------

def _prom_name(dotted: str, suffix: str = "") -> str:
    name = "onix_" + re.sub(r"[^a-zA-Z0-9_:]", "_", dotted) + suffix
    return name


def _hist_suffix(name: str) -> str:
    """Prometheus unit suffix for a registry histogram. Span histograms
    are durations; anything else (e.g. the daily supervisor's
    `daily.drift`, a total-variation ratio in [0, 1]) renders WITHOUT
    the `_seconds` suffix — a unit suffix that lies about the unit is
    worse than none."""
    return "_seconds" if name.startswith("span.") else ""


def _prom_escape(value: str) -> str:
    return value.replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return repr(f) if f != int(f) else str(int(f))


def render_prometheus(counter_snap: dict[str, int] | None = None,
                      hist_reg: HistogramRegistry | None = None,
                      gauges: dict[str, float] | None = None,
                      info: dict[str, str] | None = None) -> str:
    """The Prometheus text format (version 0.0.4): every counter as
    `onix_<name>` (dots -> underscores), every histogram as
    `onix_<name>_seconds` with cumulative `le` buckets + `_sum` +
    `_count`, gauges as given, and one `onix_build_info{...} 1` info
    metric. Output is validated by `parse_prometheus_text` in tests
    and scripts/lint.sh."""
    lines: list[str] = []
    for name, value in sorted((counter_snap or {}).items()):
        pn = _prom_name(name)
        lines.append(f"# HELP {pn} onix counter {name}")
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {int(value)}")
    for name, value in sorted((gauges or {}).items()):
        pn = _prom_name(name)
        lines.append(f"# HELP {pn} onix gauge {name}")
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_fmt(float(value))}")
    reg = hist_reg if hist_reg is not None else histograms
    for name in reg.names():
        h = reg.get(name)
        if h is None:
            continue
        pn = _prom_name(name, _hist_suffix(name))
        lines.append(f"# HELP {pn} onix log-bucketed histogram {name} "
                     f"(rel error <= {h.rel_error:.3f})")
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        for b, c in h._sorted_counts():
            cum += c
            lines.append(f'{pn}_bucket{{le="{_fmt(h.edge(b))}"}} {cum}')
        lines.append(f'{pn}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{pn}_sum {_fmt(h.sum)}")
        lines.append(f"{pn}_count {cum}")
    kv = ",".join(f'{k}="{_prom_escape(str(v))}"'
                  for k, v in sorted((info or {}).items()))
    pn = "onix_build_info"
    lines.append(f"# HELP {pn} build/config identity of this process")
    lines.append(f"# TYPE {pn} gauge")
    lines.append(f"{pn}{{{kv}}} 1" if kv else f"{pn} 1")
    return "\n".join(lines) + "\n"


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^{}]*)\})?"
    r"\s+(?P<value>[-+]?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|Inf|NaN))"
    r"(?:\s+(?P<ts>[-+]?[0-9]+))?\s*$")
_LABEL_RE = re.compile(
    r'^(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]|\\.)*)"$')


def parse_prometheus_text(text: str) -> dict[str, dict]:
    """Strict parser for the exposition format. Returns
    family base name -> {"type": ..., "samples": [(name, labels, value)]}.
    Raises ValueError on: malformed lines, samples typed before their
    TYPE line, duplicate TYPE lines, non-monotone histogram buckets, a
    histogram missing its +Inf bucket, or `_count` != the +Inf bucket.
    Deliberately strict — the in-tree gate that keeps /metrics
    scrapeable by real collectors."""
    families: dict[str, dict] = {}
    types: dict[str, str] = {}

    def base_of(name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in types \
                    and types[name[:-len(suffix)]] == "histogram":
                return name[:-len(suffix)]
        return name

    for i, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                raise ValueError(f"line {i}: malformed comment {line!r}")
            if parts[1] == "TYPE":
                name, typ = parts[2], parts[3].strip()
                if typ not in ("counter", "gauge", "histogram", "summary",
                               "untyped"):
                    raise ValueError(f"line {i}: unknown type {typ!r}")
                if name in types:
                    raise ValueError(f"line {i}: duplicate TYPE for {name}")
                types[name] = typ
                families[name] = {"type": typ, "samples": []}
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {i}: malformed sample {line!r}")
        labels: dict[str, str] = {}
        raw = m.group("labels")
        if raw:
            for part in _split_labels(raw, i):
                lm = _LABEL_RE.match(part)
                if lm is None:
                    raise ValueError(f"line {i}: malformed label {part!r}")
                labels[lm.group("k")] = re.sub(
                    r"\\(.)", lambda m: {"n": "\n"}.get(m.group(1),
                                                        m.group(1)),
                    lm.group("v"))
        base = base_of(m.group("name"))
        if base not in families:
            raise ValueError(
                f"line {i}: sample for {m.group('name')} precedes its "
                "TYPE line")
        value = float(m.group("value").replace("Inf", "inf"))
        families[base]["samples"].append((m.group("name"), labels, value))
    for name, fam in families.items():
        if fam["type"] != "histogram":
            continue
        buckets = [(lab.get("le"), v) for n, lab, v in fam["samples"]
                   if n == name + "_bucket"]
        if not buckets or buckets[-1][0] != "+Inf":
            raise ValueError(f"histogram {name}: missing +Inf bucket")
        values = [v for _, v in buckets]
        if any(b > a for b, a in zip(values, values[1:])):
            raise ValueError(f"histogram {name}: non-cumulative buckets")
        count = [v for n, _, v in fam["samples"] if n == name + "_count"]
        if not count or count[0] != values[-1]:
            raise ValueError(
                f"histogram {name}: _count != +Inf bucket")
        if not any(n == name + "_sum" for n, _, _ in fam["samples"]):
            raise ValueError(f"histogram {name}: missing _sum")
    return families


def _split_labels(raw: str, line_no: int) -> list[str]:
    """Split `k="v",k2="v2"` honoring escaped quotes inside values."""
    out, buf, in_str, esc = [], [], False, False
    for ch in raw:
        if esc:
            buf.append(ch)
            esc = False
            continue
        if ch == "\\" and in_str:
            buf.append(ch)
            esc = True
            continue
        if ch == '"':
            in_str = not in_str
            buf.append(ch)
            continue
        if ch == "," and not in_str:
            out.append("".join(buf))
            buf = []
            continue
        buf.append(ch)
    if in_str:
        raise ValueError(f"line {line_no}: unterminated label string")
    if buf:
        out.append("".join(buf))
    return [p for p in out if p]


# ---------------------------------------------------------------------------
# Process wiring: the counter observer and the exit snapshot.
# ---------------------------------------------------------------------------


def _counter_observer(name: str, delta: int, total: int) -> None:
    """Installed on `obs.counters` at import: every counter delta lands
    in the flight ring (the `counter-delta` event class), EXCEPT the
    telemetry namespace itself (a dump incrementing recorder_dumps must
    not re-enter the ring it just snapshotted)."""
    if not TRACER.enabled or name.startswith("telemetry."):
        return
    RECORDER.record("counter", name=name, delta=delta, total=total)


def _register_exit_snapshot() -> None:
    # run_tpu_queue.py sets this to a per-entry path; the child process
    # writes a full telemetry snapshot (counters + histograms) there at
    # exit, so queue entries carry dispatch/compile evidence, not bare
    # walls.
    path = os.environ.get("_ONIX_TELEMETRY_SNAPSHOT")
    if not path:
        return

    def _write():
        try:
            pathlib.Path(path).write_text(
                json.dumps(snapshot(full=True), indent=2,
                           default=repr) + "\n")
        except OSError:
            counters.inc("telemetry.snapshot_write_failed")

    atexit.register(_write)


counters.set_observer(_counter_observer)
_register_exit_snapshot()
