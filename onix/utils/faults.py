"""Declarative fault injection — the chaos harness.

Generalizes the one-off ONIX_FAULT_SWEEP hook (which only knew how to
preempt the Gibbs fit) into a fault PLAN injectable at every stage the
pipeline can die in production:

    ONIX_FAULT_PLAN="ingest:decode@2=raise,stream:batch@5=raise,\
fit:sweep@30=preempt,ckpt:save@1=torn"

Grammar: comma-separated rules `stage:point@N=action`.

  stage:point   where the fault fires. Wired sites:
                  ingest:decode   — ingest/run.decode, before any parse
                  stream:batch    — StreamingScorer.process entry
                                    (before any state mutation, so a
                                    retried batch is safe)
                  fit:sweep       — run_fit_segments superstep boundary
                  ckpt:save       — checkpoint.save
                  campaign:prepare— campaign.py host-prepare entry
                  serve:score     — BankService.score entry (before
                                    any cache/residency mutation, so
                                    the bounded serve retry replays
                                    safely — r16 serving resilience)
                  bank:admit      — ModelBank._ensure_resident entry
                                    (before any LRU mutation or H2D)
                  feedback:install— BankService.apply_feedback_filter
                                    entry (before the filter/epoch
                                    install mutates anything)
                  host:death      — hostfabric worker superstep entry
                                    (indexed by sweep; the worker dies
                                    abruptly, coordinator absorbs)
                  host:merge      — hostfabric worker collective
                                    dispatch (indexed by sweep; inside
                                    the bounded retry, pre-mutation)
                  host:ckpt       — hostfabric worker shard save entry
                                    (indexed by sweep; torn leaves the
                                    npz without its json)
  @N            for counted points (decode, batch, save): the Nth call
                to that point. For indexed points (fit:sweep, which
                passes the sweep number): the first boundary at or
                after sweep N (boundaries land on superstep edges).
  action        raise    — raise InjectedFault (a generic hard error;
                           retry/quarantine machinery must absorb it)
                preempt  — raise checkpoint.SimulatedPreemption (the
                           §5.3 preemption drill)
                torn     — cooperative: fire() RETURNS "torn" and the
                           site renders it (checkpoint.save leaves the
                           npz without its meta json — the crash-
                           between-renames torn state load_latest must
                           skip)

Every rule fires ONCE (one-shot) so the retry that follows succeeds —
the point of the harness is proving recovery, not permanent failure.
Each firing increments `obs.counters` under `faults.<stage>.<point>`.

Plans come from the ONIX_FAULT_PLAN env var (parsed once per distinct
spec) or `install_plan()` (tests, CLI --fault-plan).
"""

from __future__ import annotations

import dataclasses
import os
import threading

from onix.utils.obs import counters

_ACTIONS = ("raise", "preempt", "torn")


class InjectedFault(RuntimeError):
    """A hard failure injected by the fault plan ('raise' action)."""


@dataclasses.dataclass
class FaultRule:
    stage: str
    point: str
    n: int
    action: str
    calls: int = 0
    fired: bool = False

    def matches(self, stage: str, point: str) -> bool:
        return self.stage == stage and self.point == point

    def should_fire(self, index: int | None) -> bool:
        """Counted points pass index=None (internal call counter);
        indexed points (fit:sweep) pass their own monotone index."""
        if self.fired:
            return False
        if index is None:
            self.calls += 1
            return self.calls == self.n
        return index >= self.n


class FaultPlan:
    """A parsed set of one-shot fault rules."""

    #: Lock discipline, machine-checked by the `locks` analysis pass.
    #: The shared mutable state is the rule objects' one-shot counters
    #: (calls/fired), mutated only inside consume() under _lock; the
    #: rules list itself must never be rebound off-lock either.
    GUARDED_BY = {"rules": "_lock"}

    def __init__(self, rules: list[FaultRule], spec: str = ""):
        self.rules = rules
        self.spec = spec
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        rules: list[FaultRule] = []
        for part in filter(None, (p.strip() for p in spec.split(","))):
            try:
                where, action = part.split("=", 1)
                target, n = where.split("@", 1)
                stage, point = target.split(":", 1)
                n = int(n)
            except ValueError:
                raise ValueError(
                    f"bad fault rule {part!r}: expected "
                    "stage:point@N=action") from None
            if action not in _ACTIONS:
                raise ValueError(f"bad fault rule {part!r}: unknown action "
                                 f"{action!r} (expected one of {_ACTIONS})")
            if n < 1:
                raise ValueError(f"bad fault rule {part!r}: N must be >= 1")
            rules.append(FaultRule(stage.strip(), point.strip(), n, action))
        return cls(rules, spec=spec)

    def consume(self, stage: str, point: str,
                index: int | None = None) -> str | None:
        """The action of the first matching rule that fires now (marking
        it fired), else None."""
        with self._lock:
            for rule in self.rules:
                if rule.matches(stage, point) and rule.should_fire(index):
                    rule.fired = True
                    counters.inc(f"faults.{stage}.{point}")
                    return rule.action
        return None

    def pending(self) -> list[str]:
        """Rules that never fired — a chaos test asserting full coverage
        checks this is empty at the end."""
        return [f"{r.stage}:{r.point}@{r.n}={r.action}"
                for r in self.rules if not r.fired]


_installed: FaultPlan | None = None
_env_cache: tuple[str, FaultPlan] | None = None


def install_plan(plan: FaultPlan | str | None) -> FaultPlan | None:
    """Set (or with None, clear) the process-wide plan; overrides the
    env var. Returns the installed plan."""
    global _installed
    _installed = (FaultPlan.parse(plan) if isinstance(plan, str) else plan)
    return _installed


def reset() -> None:
    """Clear the installed plan AND the env-spec cache, so a later run
    with the SAME ONIX_FAULT_PLAN string starts with fresh one-shot
    rules (tests; also the CLI between drills)."""
    global _installed, _env_cache
    _installed = None
    _env_cache = None


def active_plan() -> FaultPlan | None:
    """The installed plan, else the ONIX_FAULT_PLAN env plan (parsed
    once per distinct spec — rule counters persist across calls)."""
    global _env_cache
    if _installed is not None:
        return _installed
    spec = os.environ.get("ONIX_FAULT_PLAN", "")
    if not spec:
        return None
    if _env_cache is None or _env_cache[0] != spec:
        _env_cache = (spec, FaultPlan.parse(spec))
    return _env_cache[1]


def fire(stage: str, point: str, index: int | None = None) -> str | None:
    """The one injection call every wired site makes. Raises for
    'raise'/'preempt'; RETURNS 'torn' (cooperative actions the site
    renders itself); returns None when no rule fires. Near-zero cost
    with no plan active."""
    plan = active_plan()
    if plan is None:
        return None
    action = plan.consume(stage, point, index)
    if action is not None:
        # r18 flight recorder: the firing itself lands in the ring
        # (richer than the counter delta: action + index), and the ring
        # is dumped NOW — the artifact holds what led UP to the fault,
        # the postmortem every faults-marker failure ships with
        # (docs/OBSERVABILITY.md). Lazy import: fault-free processes
        # never pay it, and telemetry never imports faults back.
        from onix.utils import telemetry
        if telemetry.TRACER.enabled:    # off = no ring events, no dumps
            telemetry.RECORDER.record("fault", site=f"{stage}:{point}",
                                      action=action, index=index)
            telemetry.RECORDER.dump(f"fault-{stage}-{point}",
                                    extra={"action": action,
                                           "index": index})
    if action == "raise":
        raise InjectedFault(f"injected fault at {stage}:{point}"
                            + (f" (index {index})" if index is not None
                               else ""))
    if action == "preempt":
        from onix.checkpoint import SimulatedPreemption
        raise SimulatedPreemption(
            f"injected preemption at {stage}:{point}"
            + (f" (index {index})" if index is not None else ""))
    return action
