"""Bounded-retry policy, deadlines, and quarantine — the resilience core.

The failure modes this layer exists for are INPUT failures, not clean
preemptions (those are checkpoint.py's job): one corrupt nfcapd file
used to be a poison pill the watcher retried on every poll forever, one
malformed record rejected an entire 99%-good capture, and checkpoint
integrity rested on "np.load didn't throw". The pieces here are shared
by every stage:

- `RetryPolicy` — bounded attempts with exponential backoff + decorrelated
  jitter, and the salvage decision (`strict_for_attempt`): every attempt
  but the last runs strict, the LAST attempt runs the decoder in salvage
  mode (skip malformed records/blocks, count them) so a mostly-good
  capture still lands before the file is given up on.
- `retry_call` — drive a callable under a policy (the streaming batch
  step uses it; ingest drives the policy across *polls* instead, with
  attempt counts persisted in the ledger).
- `quarantine_file` — the dead-letter move: the poison file goes to
  `quarantine/` next to its landing dir with a JSON sidecar (error,
  attempts, traceback, signature) and the caller durably marks it so it
  is never re-claimed. At-least-once delivery is preserved: quarantine
  is loud, inspectable, and reversible by an operator (move the file
  back), never a silent drop.
- `Deadline` / `run_with_deadline` — wall-clock budget for a stage; the
  thread-based wrapper bounds how long a wedged decode can hold a
  worker slot (the hung-subprocess analogue of the retry budget).

Every event flows through `obs.counters` so watcher stats, streaming
reports, and scale manifests agree on the same numbers.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import json
import pathlib
import random
import shutil
import time
import traceback as traceback_mod

from onix.utils.obs import counters


class QuarantinedInput(RuntimeError):
    """Raised when an input exhausted its retry budget and was moved to
    the dead-letter directory."""


class DeadlineExceeded(TimeoutError):
    """A stage overran its wall-clock budget."""


class Overloaded(RuntimeError):
    """A request refused by admission control (load shed) — the serving
    layer renders it as HTTP 503 with a Retry-After of
    `retry_after_s`. Shedding is a REFUSAL, not a failure: the request
    was never started, so it mutated nothing (no bank residency, no
    winner-cache entries) and an immediate retry after the hint is
    safe."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retries with exponential backoff + jitter.

    `max_attempts` counts TOTAL tries (3 = two strict tries, then one
    salvage try, then quarantine). Backoff for attempt k (1-based) is
    `base_backoff_s * 2^(k-1)` capped at `max_backoff_s`, scaled by a
    uniform jitter in [1-jitter, 1+jitter] so a directory full of
    poison files doesn't retry in lockstep. `jitter=0` makes backoff
    deterministic (tests)."""

    max_attempts: int = 3
    base_backoff_s: float = 0.5
    max_backoff_s: float = 30.0
    jitter: float = 0.25
    salvage_on_final: bool = True

    def backoff(self, attempt: int, rng: random.Random | None = None) -> float:
        """Seconds to wait AFTER failed attempt `attempt` (1-based)."""
        base = min(self.base_backoff_s * (2 ** max(attempt - 1, 0)),
                   self.max_backoff_s)
        if self.jitter <= 0:
            return base
        r = rng if rng is not None else random
        return base * r.uniform(1.0 - self.jitter, 1.0 + self.jitter)

    def strict_for_attempt(self, attempt: int) -> bool:
        """Strict decode for every attempt except the LAST, which runs
        in salvage mode (skip-and-count) so a mostly-good file still
        lands before quarantine."""
        if not self.salvage_on_final:
            return True
        return attempt < self.max_attempts

    def exhausted(self, attempts: int) -> bool:
        return attempts >= self.max_attempts


def retry_call(fn, *, policy: RetryPolicy | None = None,
               counter_prefix: str = "resilience.retry", retry_on=Exception,
               sleep=time.sleep, on_retry=None):
    """Call `fn(strict=...)` under `policy`: strict on every attempt but
    the last, salvage (strict=False) on the last, bounded backoff
    between attempts. Re-raises the final error after the budget.

    `fn` must accept a `strict` keyword (stages that have no salvage
    mode just ignore it). `retry_on` narrows which exception classes
    are retried — callers whose `fn` mutates state mid-call must
    restrict it to errors known to fire before any mutation (the
    streaming batch step retries only injected entry-point faults);
    anything else propagates immediately. `on_retry(attempt, exc)`
    observes failures."""
    policy = policy or RetryPolicy()
    last: BaseException | None = None
    for attempt in range(1, policy.max_attempts + 1):
        try:
            return fn(strict=policy.strict_for_attempt(attempt))
        except retry_on as e:
            last = e
            # lint: exempt[counters] -- namespace arrives via counter_prefix; the linter validates every counter_prefix= literal at its call site instead
            counters.inc(f"{counter_prefix}.failures")
            if on_retry is not None:
                on_retry(attempt, e)
            if attempt < policy.max_attempts:
                # lint: exempt[counters] -- namespace arrives via counter_prefix; validated at the call sites
                counters.inc(f"{counter_prefix}.retries")
                sleep(policy.backoff(attempt))
    raise last


def quarantine_file(path: str | pathlib.Path,
                    quarantine_dir: str | pathlib.Path, *,
                    error: str, attempts: int,
                    traceback: str | None = None,
                    sig: list | None = None) -> pathlib.Path:
    """Move a poison file into the dead-letter directory with a JSON
    sidecar (<name>.quarantine.json: original path, error, attempts,
    traceback, claim-time signature, timestamp). Returns the sidecar
    path. Name collisions get a numeric suffix so re-delivered poison
    never overwrites the evidence of the previous one."""
    path = pathlib.Path(path)
    qdir = pathlib.Path(quarantine_dir)
    qdir.mkdir(parents=True, exist_ok=True)
    dest = qdir / path.name
    n = 0
    while dest.exists():
        n += 1
        dest = qdir / f"{path.name}.{n}"
    try:
        shutil.move(str(path), str(dest))
    except FileNotFoundError:
        pass    # vanished under us: still record the sidecar
    sidecar = dest.with_name(dest.name + ".quarantine.json")
    sidecar.write_text(json.dumps({
        "original_path": str(path),
        "quarantined_as": str(dest),
        "error": error,
        "attempts": int(attempts),
        "traceback": traceback,
        "sig": sig,
        "quarantined_at": time.time(),
    }, indent=2))
    counters.inc("ingest.quarantined")
    return sidecar


def format_exception(e: BaseException, limit: int = 4000) -> str:
    """Traceback string for sidecars, bounded so one pathological error
    cannot bloat the dead-letter metadata."""
    return "".join(traceback_mod.format_exception(
        type(e), e, e.__traceback__))[-limit:]


@dataclasses.dataclass
class Deadline:
    """Wall-clock budget carried through a stage: check() raises
    DeadlineExceeded once expired; remaining() feeds sub-timeouts
    (e.g. subprocess timeout= arguments) so a stage's children can
    never outlive the stage's own budget."""

    seconds: float
    _t0: float = dataclasses.field(default_factory=time.monotonic)

    def remaining(self) -> float:
        return self.seconds - (time.monotonic() - self._t0)

    def expired(self) -> bool:
        return self.remaining() <= 0

    def check(self, what: str = "stage") -> None:
        if self.expired():
            counters.inc("resilience.deadline_exceeded")
            raise DeadlineExceeded(
                f"{what} exceeded its {self.seconds:.1f}s deadline")


def run_with_deadline(fn, seconds: float, *args, what: str = "call",
                      **kwargs):
    """Run `fn(*args, **kwargs)` with a wall-clock bound. On timeout the
    worker thread is abandoned (Python cannot kill it) and
    DeadlineExceeded raised — the caller's retry budget then decides the
    file's fate, instead of a wedged decode pinning a worker forever."""
    pool = concurrent.futures.ThreadPoolExecutor(
        max_workers=1, thread_name_prefix="onix-deadline")
    fut = pool.submit(fn, *args, **kwargs)
    try:
        return fut.result(timeout=seconds)
    except concurrent.futures.TimeoutError:
        counters.inc("resilience.deadline_exceeded")
        raise DeadlineExceeded(
            f"{what} exceeded its {seconds:.1f}s deadline") from None
    finally:
        # wait=False: a wedged fn must not convert the timeout into a
        # blocked shutdown — the thread is abandoned, not joined.
        pool.shutdown(wait=False)
