"""Observability: profiler trace scopes, run event log, throughput meters.

The reference has no purpose-built tracing or metrics (SURVEY.md §5.1,
§5.5 — it leaned on the Spark web UI, YARN logs, and lda-c's stdout
likelihood prints). onix makes the three judged observables first-class:

- `trace_scope(name)` — jax.profiler annotation around the hot loops so
  a TensorBoard/Perfetto trace of a scoring run shows named Gibbs-sweep
  and scoring-scan spans; `start_trace(dir)` dumps a full trace when
  ONIX_PROFILE_DIR (or the call) asks for one.
- `RunLog` — append-only JSONL event stream per run (stage boundaries,
  per-sweep likelihood, checkpoint saves, faults) next to the results.
- `Meter` — wall-clock + items/sec for the events-scored/sec/chip
  number (BASELINE.json `metric`), reported in the run manifest.
"""

from __future__ import annotations

import contextlib
import json
import os
import pathlib
import threading
import time


#: Declared counter namespaces: the first dotted component of every
#: literal `counters.inc`/`note_max`/`get` key (and every f-string
#: key's literal prefix, and every `counter_prefix=` literal) must be a
#: key here — machine-checked by `python -m onix.analysis` (the
#: `counters` pass), because a typo'd namespace is a counter that
#: silently never aggregates into the manifests that snapshot by
#: prefix. Dead namespaces (declared, never used) are findings too.
#: Renders into docs/ROBUSTNESS.md (generated section
#: `counter-namespaces`).
COUNTER_NAMESPACES: dict[str, str] = {
    "bank": "model-bank residency/cache/dispatch events (onix/serving)",
    "bench": "bench.py harness self-reporting (probe failures, stale artifacts)",
    "campaign": "campaign orchestrator retries/preemptions (pipelines/campaign.py)",
    "ckpt": "checkpoint/model integrity events (digest mismatches)",
    "daily": "continuous-operation supervisor events (warm/cold refits, drift fallbacks, ledger refusals, poison-day rollbacks; pipelines/daily.py)",
    "faults": "injected chaos-plan firings, as faults.<stage>.<point>",
    "fleet": "fleet-batched refit supervisor events (warm/cold tenant-days, drift cold refits, per-tenant quarantines, nudge applications; pipelines/fleet.py)",
    "host": "multi-host fit fabric events (heartbeats, death detection, shard quarantine, restart/rebalance; parallel/hostfabric.py)",
    "feedback": "analyst feedback loop events (rescored events, skipped nudges)",
    "ingest": "watcher/mpingest retry + quarantine events",
    "pallas": "Pallas kernel probe/fallback events",
    "resilience": "RetryPolicy/Deadline events (utils/resilience.py)",
    "salvage": "salvage-mode decode skip tallies, per format",
    "scale": "scale-runner resume/discard events (pipelines/scale.py)",
    "serve": "serving admission/degradation events (shed, deadline, fallback)",
    "stream": "streaming scorer shape-lattice + prefetch events",
    "telemetry": "telemetry layer self-reporting (spans recorded, flight-recorder dumps; utils/telemetry.py)",
}


class CounterRegistry:
    """Process-wide named event counters — the one place every
    resilience event (retry, quarantine, salvage, injected fault,
    checkpoint digest mismatch) is tallied, so watcher stats, streaming
    stage reports, and bench/scale manifests all read the same numbers
    instead of each keeping a private ledger. Thread-safe; names are
    dotted paths (`ingest.quarantined`, `salvage.skipped_records`)."""

    #: Lock discipline, machine-checked by the `locks` analysis pass.
    GUARDED_BY = {"_counts": "_lock", "_observer": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        # Optional delta observer (utils/telemetry.py installs the
        # flight-recorder feed here at import): called as
        # observer(name, delta, total) AFTER the lock is released, so
        # an observer can never deadlock the registry. None = off.
        self._observer = None

    def set_observer(self, fn) -> None:
        with self._lock:
            self._observer = fn

    def inc(self, name: str, n: int = 1) -> int:
        with self._lock:
            self._counts[name] = total = self._counts.get(name, 0) + int(n)
        obs_fn = self._observer
        if obs_fn is not None:
            obs_fn(name, int(n), total)
        return total

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def note_max(self, name: str, value: int) -> int:
        """High-water-mark counter: keep the LARGEST value ever noted
        (e.g. `serve.queue_depth_peak`). Same namespace and snapshot
        path as the event counters, so manifests carry gauges and
        tallies through one registry."""
        moved = False
        with self._lock:
            cur = self._counts.get(name, 0)
            if int(value) > cur:
                self._counts[name] = int(value)
                cur = int(value)
                moved = True
        obs_fn = self._observer
        if moved and obs_fn is not None:
            obs_fn(name, 0, cur)
        return cur

    def snapshot(self, prefix: str = "") -> dict[str, int]:
        """Copy of the current counts (optionally only names under
        `prefix`) — what manifests embed."""
        with self._lock:
            return {k: v for k, v in sorted(self._counts.items())
                    if k.startswith(prefix)}

    def reset(self, prefix: str = "") -> None:
        with self._lock:
            if not prefix:
                self._counts.clear()
            else:
                for k in [k for k in self._counts if k.startswith(prefix)]:
                    del self._counts[k]


#: The process-global registry (tests reset() it between cases).
counters = CounterRegistry()


class OccupancyClock:
    """Overlap-exact wall accounting for pipelined multi-stage runs —
    the shared discipline behind the r14 campaign orchestrator
    (onix/pipelines/campaign.py), generalizing the streaming
    prefetcher's rule that only CONSUMER-BLOCKED seconds count as wait
    (streaming.py prefetch_wait; docs/PERF.md r10).

    `busy(name)` marks a stage busy on the calling thread; stages may
    run concurrently on different threads. `blocked(name)` records
    consumer-blocked seconds — time a thread spent waiting on another
    stage's output, the pipeline's barrier stalls. Derived numbers:

      * busy_s[name]    — per-stage busy seconds (sum over threads);
      * union_busy_s    — wall seconds during which >= 1 stage was
                          busy (active-count 0→1/1→0 transitions);
      * overlap_s       — Σ busy − union: seconds of genuinely
                          concurrent stage work (0 in a sequential
                          run — the assertable difference between the
                          orchestrator's two arms);
      * the stage-sum identity — for any single thread, Σ its busy
                          spans + Σ its blocked spans + its idle ==
                          its elapsed span. The campaign asserts it
                          for the driver thread (check_stage_sum).

    Thread-safe; snapshot at quiescence (open busy spans are not yet
    in union_busy_s)."""

    #: Lock discipline, machine-checked by the `locks` analysis pass:
    #: stages run on several threads; every tally mutates under _lock.
    GUARDED_BY = {"busy_s": "_lock", "blocked_s": "_lock",
                  "_active": "_lock", "_active_since": "_lock",
                  "union_busy_s": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self.busy_s: dict[str, float] = {}
        self.blocked_s: dict[str, float] = {}
        self._active = 0
        self._active_since = 0.0
        self.union_busy_s = 0.0

    @contextlib.contextmanager
    def busy(self, name: str):
        t0 = time.perf_counter()
        with self._lock:
            if self._active == 0:
                self._active_since = t0
            self._active += 1
        try:
            yield
        finally:
            t1 = time.perf_counter()
            with self._lock:
                self._active -= 1
                if self._active == 0:
                    self.union_busy_s += t1 - self._active_since
                self.busy_s[name] = (self.busy_s.get(name, 0.0)
                                     + (t1 - t0))

    @contextlib.contextmanager
    def blocked(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            with self._lock:
                self.blocked_s[name] = (self.blocked_s.get(name, 0.0)
                                        + (time.perf_counter() - t0))

    @property
    def span_s(self) -> float:
        return time.perf_counter() - self._t0

    def check_stage_sum(self, stage_names, blocked_names=None,
                        span_s: float | None = None,
                        tol_s: float = 0.25) -> tuple[bool, float]:
        """The stage-sum identity for one thread's stages: Σ busy +
        Σ blocked must not exceed the thread's span, and the residual
        (idle) must be non-negative — accounted time can never exceed
        wall. Returns (ok, residual_idle_s); `tol_s` absorbs clock
        granularity."""
        span = self.span_s if span_s is None else span_s
        with self._lock:
            accounted = sum(self.busy_s.get(n, 0.0) for n in stage_names)
            accounted += sum(
                self.blocked_s.get(n, 0.0)
                for n in (blocked_names if blocked_names is not None
                          else self.blocked_s))
        residual = span - accounted
        return residual >= -tol_s, residual

    def snapshot(self) -> dict:
        with self._lock:
            total = sum(self.busy_s.values())
            return {
                "span_s": round(time.perf_counter() - self._t0, 3),
                "busy_s": {k: round(v, 3)
                           for k, v in sorted(self.busy_s.items())},
                "blocked_s": {k: round(v, 3)
                              for k, v in sorted(self.blocked_s.items())},
                "union_busy_s": round(self.union_busy_s, 3),
                "overlap_s": round(max(total - self.union_busy_s, 0.0), 3),
            }


def enable_compile_cache(cache_dir: str | pathlib.Path) -> None:
    """Persistent XLA compilation cache. First compiles through the
    device tunnel cost 5-30s per program; caching them on disk makes
    every later cold process warm-start (safe to call repeatedly).

    ACCELERATOR BACKENDS ONLY: on the CPU backend the cache is a no-op
    by design. CPU compiles are seconds (nothing to amortize), and
    warm-cache deserialization has been observed MIS-EXECUTING on the
    CPU jax in this container — repeated identical `run_scale` calls
    returned different bottom-k sets (planted hits 50/44/5/0 across
    runs) and aborted with glibc heap corruption at teardown; every
    run with a cold cache is deterministic. A cache that can silently
    corrupt the judged winners is worse than no cache."""
    import jax
    if jax.default_backend() == "cpu":
        return
    path = pathlib.Path(cache_dir)
    path.mkdir(parents=True, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(path))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


@contextlib.contextmanager
def trace_scope(name: str):
    """Named span in the device profile; near-zero cost when no trace is
    being collected."""
    import jax.profiler
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def maybe_trace(out_dir: str | None = None):
    """Collect a full profiler trace if `out_dir` or ONIX_PROFILE_DIR is
    set; otherwise a no-op. View with TensorBoard or Perfetto."""
    import jax.profiler
    target = out_dir or os.environ.get("ONIX_PROFILE_DIR")
    if not target:
        yield None
        return
    pathlib.Path(target).mkdir(parents=True, exist_ok=True)
    jax.profiler.start_trace(target)
    try:
        yield target
    finally:
        jax.profiler.stop_trace()


class RunLog:
    """Append-only JSONL event log (SURVEY.md §5.5).

    One line per event: {"t": epoch_s, "event": ..., **fields}. The file
    is opened per-append so a preempted run loses at most one line.
    """

    def __init__(self, path: str | pathlib.Path | None):
        self.path = pathlib.Path(path) if path else None
        if self.path is not None:
            self.path.parent.mkdir(parents=True, exist_ok=True)

    def emit(self, event: str, **fields) -> None:
        if self.path is None:
            return
        rec = {"t": round(time.time(), 3), "event": event, **fields}
        with open(self.path, "a") as fh:
            fh.write(json.dumps(rec) + "\n")

    @contextlib.contextmanager
    def stage(self, name: str, **fields):
        """Log stage start/end (with wall seconds) around a block."""
        self.emit("stage_start", stage=name, **fields)
        t0 = time.perf_counter()
        try:
            yield
        except BaseException as e:
            self.emit("stage_error", stage=name, error=repr(e),
                      wall_s=round(time.perf_counter() - t0, 3))
            raise
        self.emit("stage_end", stage=name,
                  wall_s=round(time.perf_counter() - t0, 3))


# ---------------------------------------------------------------------------
# Roofline accounting (docs/PERF.md).
#
# The judged hot loops are MEMORY-bound on every platform measured: the
# scoring scan is two table-row gathers + a score write per event, and
# the Gibbs sweep is bounded by the n_dk/n_wk scatter-add (PERF.md "the
# scatter IS the sweep's ceiling"). The honest efficiency number is
# therefore achieved bytes/s against the device's peak memory
# bandwidth, not FLOP/s. bench.py derives each component's modeled
# bytes/item from its shape and reports `detail.roofline`, so a
# throughput regression shows up as a tracked fraction-of-peak drop
# instead of a prose claim.
# ---------------------------------------------------------------------------

# Chip HBM peaks, bytes/s (vendor specs), keyed on jax device_kind
# prefixes. The tunneled accelerator this repo measures on is a
# v5 lite (819 GB/s HBM BW).
_HBM_PEAK_BYTES_PER_S = {
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,          # v5p spec (2765 GB/s HBM2e)
    "TPU v4": 1228e9,
    "TPU v6": 1640e9,
}


def measured_host_bandwidth(size_bytes: int = 1 << 28) -> float:
    """Live streaming-copy probe of the HOST's memory bandwidth
    (read + write bytes over the best of three big memcpys). The CPU
    fallback has no spec sheet to cite — this anchors its roofline
    denominator in a measurement on the same box, same run."""
    import numpy as np
    n = size_bytes // 8
    src = np.ones(n, np.float64)
    dst = np.empty_like(src)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        np.copyto(dst, src)
        best = min(best, time.perf_counter() - t0)
    return 2.0 * n * 8 / max(best, 1e-9)


def device_peak_bytes_per_s() -> tuple[float | None, str]:
    """(peak bytes/s, provenance string) for the default device: the
    HBM spec for known TPU kinds, a live copy probe for the CPU
    fallback, (None, ...) for unknown accelerators (a made-up
    denominator would fabricate the fraction-of-peak)."""
    import jax
    dev = jax.devices()[0]
    kind = str(getattr(dev, "device_kind", ""))
    for prefix, peak in _HBM_PEAK_BYTES_PER_S.items():
        if kind.startswith(prefix):
            return peak, f"{prefix} HBM spec"
    if dev.platform == "cpu":
        return measured_host_bandwidth(), "host streaming-copy probe"
    return None, f"unknown device kind {kind!r}"


def gibbs_sweep_bytes_per_token(k_topics: int) -> float:
    """Modeled memory traffic per sampled token (docs/PERF.md roofline):
    n_dk[d] and n_wk[w] row read + scatter write-back (4·K·4 B) plus the
    token stream (d, w, z: 12 B). Shared by bench.py's gibbs_sweep AND
    gibbs_fit_effective roofline entries — the fit loop samples the same
    tokens through the same sweep kernel, so a widening gap between the
    two fractions is fit-loop overhead (dispatch, ll evals, wrapping),
    which is exactly the number the superstep work tracks."""
    return 4 * k_topics * 4 + 12


def gibbs_pallas_bytes_per_token(k_topics: int, n_rows: int,
                                 block_size: int) -> float:
    """Modeled HBM traffic per token for the Pallas fused sample+count
    block step (onix/models/pallas_gibbs.py; docs/PERF.md "Pallas fused
    sample+count"): the gathered n_dk[d]/n_wk[w] row reads plus the
    n_dk row scatter write-back (3·K·4 B), the pre-generated noise row
    written by the RNG and read by the kernel (2·K·4 B), the token
    stream (w, z_old in, z_new out: 12 B), and the dense [V, K] n_wk
    delta flush amortized over the block (V·K·4 / B). The n_wk
    write-back that the scatter model charges per token is gone — that
    is the kernel's whole point — so on collision-dense shapes the
    pallas model moves MORE bytes per token than the scatter model
    only via the noise rows, while removing the serialization."""
    return (5 * k_topics * 4 + 12
            + n_rows * k_topics * 4 / max(block_size, 1))


def gibbs_sparse_bytes_per_token(k_topics: int, n_active: int,
                                 mh_steps: int, n_docs: int = 0,
                                 n_vocab: int = 0,
                                 sweep_tokens: int = 0) -> float:
    """Modeled memory traffic per token for the r11 sparse O(K_active)
    sampler arm (lda_gibbs sampler_form="sparse"; docs/PERF.md "sparse
    sampler family"): the per-doc active block gathers (ids + counts +
    stale-phi values: 3·A·4 B), per MH proposal the F+-tree bisection
    (ceil(log2 K) scalar CDF gathers) plus ~10 scalar target/proposal
    gathers and 12 B of uniforms, the six rank-1 count scatters
    (read+write: 48 B), and the token stream (16 B). When the sweep
    shape is given, the per-sweep stale-table rebuild (top-A over
    [D,K] + the [V,K] CDF: read + write) is amortized over the sweep's
    tokens — the honest charge for the table freshness the MH
    correction leans on. The whole point vs gibbs_sweep_bytes_per_token
    (4·K·4 + 12): traffic scales with A + mh·log K, not K."""
    import math
    log_k = math.ceil(math.log2(max(k_topics, 2)))
    per_token = (3 * n_active * 4
                 + mh_steps * ((log_k + 10) * 4 + 12)
                 + 48 + 16)
    if n_docs and n_vocab and sweep_tokens:
        build = (n_docs * k_topics * 4            # top_k read of n_dk
                 + 2 * n_docs * n_active * 4      # act tables write
                 + 3 * n_vocab * k_topics * 4)    # phi read + cdf r/w
        per_token += build / sweep_tokens
    return per_token


def fleet_refit_bytes_per_token(k_topics: int, n_sweeps: int) -> float:
    """Modeled memory traffic per stacked PADDED token across one
    tenant's fleet refit (onix/models/fleet_gibbs.py; bench.py
    `daily_fleet` roofline): the count build (one n_dk/n_wk row
    scatter + the token stream: 4·K·4 + 12 B), then `n_sweeps` Gibbs
    sweeps at the sweep kernel's per-token traffic
    (gibbs_sweep_bytes_per_token), then the burn-in accumulator adds
    (2·K·4 B per sweep per token's rows, charged per token) and the
    two boundary ll evaluations (2·(2·K·4 + 12) B). Padded tokens move
    the same bytes as real ones — that is what `padding_stats`'
    token_pad_waste_frac prices — so the model charges the PADDED
    stream and the bench divides by padded tokens·tenants."""
    build = 4 * k_topics * 4 + 12
    sweeps = n_sweeps * (gibbs_sweep_bytes_per_token(k_topics)
                         + 2 * k_topics * 4)
    ll = 2 * (2 * k_topics * 4 + 12)
    return build + sweeps + ll


def bank_score_bytes_per_event(k_topics: int, dtype_bytes: int = 4) -> float:
    """Modeled memory traffic per scored event through the model bank's
    batched program (onix/serving/model_bank.py; bench.py `model_bank`
    roofline): the two bank-row gathers (θ_bank[slot, d], φ_bank[slot,
    w]: 2·K·dtype B — the tenant axis folds into the gather index, so
    the TENANT gather is these same rows, charged once), the per-event
    token stream (d, w ids + mask: 12 B), the request's tenant slot
    read amortized per event (≈4 B charged flat), and the f32 score
    write feeding selection (4 B). Identical per-event traffic to the
    single-tenant scan's model (bench `_roofline_detail`) plus the slot
    read — which is exactly the claim: banking N tenants adds a slot
    gather, not N× dispatch overhead."""
    return 2 * k_topics * dtype_bytes + 12 + 4 + 4


def fused_serve_bytes_per_event(k_topics: int, n_filter_entries: int = 0,
                                n_events: int = 0, max_results: int = 0,
                                mode: str = "dot") -> float:
    """Modeled HBM traffic per event for the r15 fused serving kernel
    (onix/models/pallas_serve.py; bench.py `fused_serve` roofline).
    Per event: the score operands — mode "dot": the two gathered
    theta/phi rows written by the outside gather and read by the
    kernel (2·2·K·4 B: the materialize-then-stream cost the kernel
    pays for Mosaic's missing gather rule, charged honestly at both
    ends); mode "min2"/"scores": the pre-gathered f32 score columns
    (2·4 / 4 B) plus the same gather's read side (4 B each) — plus the
    key stream (word lo half 4 B + pair halves 8 B) and the pad mask
    (4 B). Per CALL, amortized over the events: the FILTER SEARCH
    BYTES — every sentinel-padded table entry's (hi, lo) uint32 pair
    streams HBM→VMEM exactly once (8 B/entry; the per-tile compare
    sweep then re-reads it from VMEM for free, which is the fused
    arm's membership claim) — and the single winner flush
    (max_results·8 B, once per request instead of once per chunk).
    The XLA arm's corresponding model re-reads candidates between its
    three programs; the DIFFERENCE between the two models is the HBM
    round-trip the fusion removes."""
    if mode == "dot":
        per_event = 4 * k_topics * 4
    elif mode == "min2":
        per_event = 2 * (4 + 4)
    else:
        per_event = 4 + 4
    per_event += 4 + 8 + 4
    per_call = n_filter_entries * 8 + max_results * 8
    return per_event + per_call / max(n_events, 1)


def svi_estep_bytes_per_pair(k_topics: int, iters: float) -> float:
    """Modeled memory traffic per deduped (doc, bucket) pair of the
    streaming SVI step (bench.py `streaming` roofline; docs/PERF.md
    r10): per local E-step iteration, the gamma-row gather for
    elog_theta (K·4 B), the cached elog_beta row read (K·4 B), and the
    phi scatter-add back into gamma (K·4 B) — 3·K·4 B/iteration — plus
    the one-time elog_beta row materialization and the scoring
    gather-dot + score write (2·K·4 + 4 B). `iters` is the modeled
    iteration count; artifacts pass the warm-pass length
    (svi_warm_iters) as the floor every pair pays, so the fraction is
    a LOWER bound on achieved traffic (compacted extended iterations
    move less than the model charges full-block)."""
    return iters * 3 * k_topics * 4 + 2 * k_topics * 4 + 4


def roofline(n_items: int, wall_s: float, bytes_per_item: float,
             peak_bytes_per_s: float | None) -> dict:
    """One component's roofline entry: achieved bytes/s from the
    modeled per-item traffic, and the fraction of the peak it reaches
    (None when no trustworthy peak exists)."""
    achieved = n_items * bytes_per_item / max(wall_s, 1e-9)
    return {
        "modeled_bytes_per_item": round(float(bytes_per_item), 1),
        "achieved_bytes_per_s": round(achieved, 1),
        "fraction_of_peak": (round(achieved / peak_bytes_per_s, 4)
                             if peak_bytes_per_s else None),
    }


class Meter:
    """items/sec over a wall-clock window (perf_counter based)."""

    def __init__(self):
        self.t0 = time.perf_counter()
        self.items = 0

    def add(self, n: int) -> None:
        self.items += int(n)

    @property
    def seconds(self) -> float:
        return time.perf_counter() - self.t0

    @property
    def rate(self) -> float:
        dt = self.seconds
        return self.items / dt if dt > 0 else 0.0


# Bottom import on purpose: obs is the one module every stage already
# imports, so pulling telemetry in here guarantees the flight-recorder
# counter observer (telemetry installs it at its own import) is live in
# EVERY process — chaos drills that only import faults/obs still get
# ring events, and run_tpu_queue.py's per-entry exit snapshot (the
# _ONIX_TELEMETRY_SNAPSHOT handshake) is registered no matter which
# entry point the child runs. Safe against the obs<->telemetry cycle:
# everything telemetry needs from obs is defined above this line.
from onix.utils import telemetry as _telemetry  # noqa: E402,F401
