"""Observability: profiler trace scopes, run event log, throughput meters.

The reference has no purpose-built tracing or metrics (SURVEY.md §5.1,
§5.5 — it leaned on the Spark web UI, YARN logs, and lda-c's stdout
likelihood prints). onix makes the three judged observables first-class:

- `trace_scope(name)` — jax.profiler annotation around the hot loops so
  a TensorBoard/Perfetto trace of a scoring run shows named Gibbs-sweep
  and scoring-scan spans; `start_trace(dir)` dumps a full trace when
  ONIX_PROFILE_DIR (or the call) asks for one.
- `RunLog` — append-only JSONL event stream per run (stage boundaries,
  per-sweep likelihood, checkpoint saves, faults) next to the results.
- `Meter` — wall-clock + items/sec for the events-scored/sec/chip
  number (BASELINE.json `metric`), reported in the run manifest.
"""

from __future__ import annotations

import contextlib
import json
import os
import pathlib
import time


def enable_compile_cache(cache_dir: str | pathlib.Path) -> None:
    """Persistent XLA compilation cache. First compiles through the
    device tunnel cost 5-30s per program; caching them on disk makes
    every later cold process warm-start (safe to call repeatedly)."""
    import jax
    path = pathlib.Path(cache_dir)
    path.mkdir(parents=True, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(path))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


@contextlib.contextmanager
def trace_scope(name: str):
    """Named span in the device profile; near-zero cost when no trace is
    being collected."""
    import jax.profiler
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def maybe_trace(out_dir: str | None = None):
    """Collect a full profiler trace if `out_dir` or ONIX_PROFILE_DIR is
    set; otherwise a no-op. View with TensorBoard or Perfetto."""
    import jax.profiler
    target = out_dir or os.environ.get("ONIX_PROFILE_DIR")
    if not target:
        yield None
        return
    pathlib.Path(target).mkdir(parents=True, exist_ok=True)
    jax.profiler.start_trace(target)
    try:
        yield target
    finally:
        jax.profiler.stop_trace()


class RunLog:
    """Append-only JSONL event log (SURVEY.md §5.5).

    One line per event: {"t": epoch_s, "event": ..., **fields}. The file
    is opened per-append so a preempted run loses at most one line.
    """

    def __init__(self, path: str | pathlib.Path | None):
        self.path = pathlib.Path(path) if path else None
        if self.path is not None:
            self.path.parent.mkdir(parents=True, exist_ok=True)

    def emit(self, event: str, **fields) -> None:
        if self.path is None:
            return
        rec = {"t": round(time.time(), 3), "event": event, **fields}
        with open(self.path, "a") as fh:
            fh.write(json.dumps(rec) + "\n")

    @contextlib.contextmanager
    def stage(self, name: str, **fields):
        """Log stage start/end (with wall seconds) around a block."""
        self.emit("stage_start", stage=name, **fields)
        t0 = time.perf_counter()
        try:
            yield
        except BaseException as e:
            self.emit("stage_error", stage=name, error=repr(e),
                      wall_s=round(time.perf_counter() - t0, 3))
            raise
        self.emit("stage_end", stage=name,
                  wall_s=round(time.perf_counter() - t0, 3))


class Meter:
    """items/sec over a wall-clock window (perf_counter based)."""

    def __init__(self):
        self.t0 = time.perf_counter()
        self.items = 0

    def add(self, n: int) -> None:
        self.items += int(n)

    @property
    def seconds(self) -> float:
        return time.perf_counter() - self.t0

    @property
    def rate(self) -> float:
        dt = self.seconds
        return self.items / dt if dt > 0 else 0.0
