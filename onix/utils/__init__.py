from onix.utils.features import (  # noqa: F401
    shannon_entropy,
    entropy_array,
    quantile_edges,
    digitize,
    subdomain_split,
    VALID_TLDS,
)
