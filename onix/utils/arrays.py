"""Numeric array helpers shared across layers (models and pipelines
both depend on utils, never on each other)."""

from __future__ import annotations

import numpy as np


def unique_inverse(arr: np.ndarray,
                   chunk: int = 1 << 25) -> tuple[np.ndarray, np.ndarray]:
    """np.unique(arr, return_inverse=True), restructured for the
    10⁸-element path where the CARDINALITY is tiny (hundreds of words,
    ~10⁵ docs/pairs) while the array is huge: a full argsort + inverse
    scatter — what np.unique does — is mostly wasted memory traffic.
    Instead: per-chunk unique (cache-sized sorts), merge the small
    uniques, then one binary-search pass for the inverse. Identical
    output; ~4x faster at 2x10⁸ elements."""
    n = arr.shape[0]
    if n <= chunk:
        return np.unique(arr, return_inverse=True)
    u = np.unique(np.concatenate([
        np.unique(arr[lo:lo + chunk]) for lo in range(0, n, chunk)]))
    inv = np.empty(n, np.int64)
    for lo in range(0, n, chunk):
        inv[lo:lo + chunk] = np.searchsorted(u, arr[lo:lo + chunk])
    return u, inv
