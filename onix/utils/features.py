"""Feature utilities shared by the word-creation pipelines.

The reference computes these in Scala UDFs inside Spark jobs — string
entropy and subdomain decomposition for DNS words, quantile binning for
flow words (SURVEY.md §2.1 #5-#7). onix implements them vectorized over
NumPy arrays so a day of telemetry is transformed without a JVM, and the
bin edges become static metadata the TPU scoring path can reuse.
"""

from __future__ import annotations

import math
from collections import Counter

import numpy as np

# A practical set of real TLDs for the DNS "valid TLD" feature
# (SURVEY.md §2.1 #6: "TLD validity"). The reference carried a
# top-domains list file; a compact builtin set avoids a data dependency.
VALID_TLDS = frozenset("""
com org net edu gov mil int io co us uk de fr jp cn ru br in au ca it nl
es se no fi dk pl ch at be cz pt gr hu ie ro sk bg hr lt lv ee si lu mt
cy tr ua by kz mx ar cl pe ve uy py bo ec cr pa do gt hn sv ni cu jm tt
za eg ma ng ke gh tz ug dz tn ly sn zm zw mz ao cm ci
kr tw hk sg my th vn ph id pk bd lk np mm kh la mn
il sa ae qa kw bh om jo lb sy iq ir ye af
nz fj pg info biz name mobi aero asia cat coop jobs museum pro tel
travel xxx arpa root local onion test example invalid localhost
""".split())


def shannon_entropy(s: str) -> float:
    """Character-distribution Shannon entropy in bits (0.0 for empty)."""
    if not s:
        return 0.0
    n = len(s)
    return -sum(c / n * math.log2(c / n) for c in Counter(s).values())


def entropy_array(strings) -> np.ndarray:
    """`shannon_entropy` over an array of strings, vectorized: one
    code-point buffer for ALL strings, one group-by-(string, char)
    unique, one weighted bincount. Identical values to the scalar
    Counter form (character-level, unicode-aware) at NumPy speed —
    call it on UNIQUE strings and broadcast through the inverse index
    (the words.py pattern); per-row Python entropy was the DNS/proxy
    10⁸-row bottleneck (VERDICT r2 weak #4)."""
    strs = list(strings)
    n = len(strs)
    out = np.zeros(n, np.float64)
    if n == 0:
        return out.astype(np.float32)
    lens = np.fromiter((len(s) for s in strs), np.int64, n)
    if int(lens.sum()) == 0:
        return out.astype(np.float32)
    # utf-32-le of the concatenation = one uint32 code point per char.
    codes = np.frombuffer("".join(strs).encode("utf-32-le"),
                          np.uint32).astype(np.int64)
    seg = np.repeat(np.arange(n, dtype=np.int64), lens)
    key = seg * 0x110000 + codes          # code points < 0x110000
    uk, counts = np.unique(key, return_counts=True)
    ks = uk // 0x110000                   # which string each count belongs to
    p = counts / lens[ks]
    out = np.bincount(ks, weights=-p * np.log2(p), minlength=n)
    return out.astype(np.float32)


def qname_features(qnames) -> dict[str, np.ndarray]:
    """DNS-name word features, computed per input name: subdomain
    length, label count, TLD validity, subdomain entropy.

    Intended to run on the UNIQUE qnames of a day (tiny vs the row
    count — broadcast the result through the factorize codes); the
    Python loop here is over uniques only, and the entropy is the
    vectorized buffer form."""
    n = len(qnames)
    sub_len = np.zeros(n, np.float64)
    n_labels = np.zeros(n, np.int64)
    tld_ok = np.zeros(n, np.int64)
    subs: list[str] = [""] * n
    for i, q in enumerate(qnames):
        sub, _sld, nl, ok = subdomain_split(str(q))
        subs[i] = sub
        sub_len[i] = len(sub)
        n_labels[i] = min(nl, 6)
        tld_ok[i] = int(ok)
    return {"sub_len": sub_len, "n_labels": n_labels, "tld_ok": tld_ok,
            "sub_entropy": entropy_array(subs)}


# Above this size, quantile edges are fitted on a deterministic stride
# sample. Fitting coarse bin edges (n_bins ~ 5) needs quantiles to
# ~1e-3 accuracy; a 4M-element stride sample delivers that while a full
# np.quantile at 10^8 elements spends tens of seconds sorting — pure
# waste on the billion-event path.
_QUANTILE_SAMPLE_MAX = 1 << 22


def _edge_sample(values: np.ndarray) -> np.ndarray:
    """Deterministic stride sample for edge fitting (same input ->
    same edges; fitted edges are archived in the run manifest, so
    apply-mode reproducibility is exact either way)."""
    values = np.asarray(values, dtype=np.float64)
    if values.size > _QUANTILE_SAMPLE_MAX:
        stride = -(-values.size // _QUANTILE_SAMPLE_MAX)   # ceil div
        values = values[::stride]
    return values


def quantile_edges(values: np.ndarray, n_bins: int,
                   tail_qs: tuple = ()) -> np.ndarray:
    """Interior quantile cut points (n_bins - 1 edges) for equal-mass
    bins, plus optional extra upper-tail cut points (one np.quantile
    pass over one sample for both).

    The flow word binning of the reference (SURVEY.md §2.1 #5:
    "quantile-binned bytes, packets, and time-of-day").
    """
    if n_bins < 1:
        raise ValueError("n_bins must be >= 1")
    # Sorted: above ~100 bins the interior quantiles pass the 0.99/0.999
    # tail cut points, and unsorted qs return unsorted edges — searchsorted
    # (digitize) then silently misbins everything past the inversion.
    qs = np.sort(np.concatenate([np.linspace(0.0, 1.0, n_bins + 1)[1:-1],
                                 np.asarray(tail_qs, np.float64)]))
    values = _edge_sample(values)
    if values.size == 0:
        return np.zeros(len(qs), dtype=np.float64)
    return np.quantile(values, qs)


def tail_quantile_edges(values: np.ndarray, n_bins: int,
                        tail_qs: tuple = (0.99, 0.999)) -> np.ndarray:
    """Equal-mass interior edges PLUS upper-tail cut points.

    Uniform quantile bins put ~1/n_bins of the event mass in the top
    bin, so any magnitude beyond the background's support lands in a
    bin it shares with ordinary large values — on independent
    session-machine telemetry (synth2.py) this made 40-80-char
    exfiltration URIs word-identical to 17-char asset paths and the
    detector blind to them (docs/RECALL_r05_sessions.json, "before"
    arm). Rarity detection needs resolution where the rare things
    live: two extra edges at the 99th / 99.9th percentile cap the top
    bin at 0.1% mass, so out-of-support magnitudes isolate into words
    that are rare BY CONSTRUCTION. In-support behavior is unchanged
    (the uniform edges are identical); the extra bins stay within
    every word spec's 6-bit field. Duplicate edges (discrete or
    short-tailed features where q99 equals an interior edge) are
    harmless: they produce empty bins, not misbinned values."""
    return quantile_edges(values, n_bins, tail_qs=tail_qs)


def digitize(values: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Bin index in [0, len(edges)] per value (right-open bins)."""
    return np.searchsorted(np.asarray(edges), np.asarray(values),
                           side="right").astype(np.int32)


def subdomain_split(qname: str) -> tuple[str, str, int, bool]:
    """Decompose a DNS query name.

    Returns (subdomain, second_level_domain, n_labels, tld_is_valid).
    `www.mail.example.com` -> ("www.mail", "example", 4, True).
    """
    name = qname.rstrip(".").lower()
    if not name:
        return "", "", 0, False
    labels = name.split(".")
    n = len(labels)
    tld_valid = labels[-1] in VALID_TLDS
    if n == 1:
        return "", labels[0], 1, tld_valid
    sld = labels[-2]
    sub = ".".join(labels[:-2])
    return sub, sld, n, tld_valid
