"""The OA batch engine: results CSV -> enriched per-date UI data files.

Equivalent of the reference's `start_oa.py --date <d> --type <t>`
(SURVEY.md §3.3): fetch the day's scored results, run the enrichment
loop (GeoIP, domain context, reputation plugins — all offline-capable,
see components.py), and emit the per-date JSON/CSV files the dashboards
read, keyed by date exactly like the reference UI's `#date=` routing
(reference README.md:55-56).

Output layout under `cfg.oa.data_dir`:

    <datatype>/<YYYYMMDD>/suspicious.csv    enriched analyst table
    <datatype>/<YYYYMMDD>/suspicious.json   same rows for the UI fetch
    <datatype>/<YYYYMMDD>/summary.json      stats/histogram/timeline
    <datatype>/<YYYYMMDD>/graph.json        network graph nodes+links
    <datatype>/<YYYYMMDD>/storyboard.json   per-actor threat cards
    <datatype>/<YYYYMMDD>/geo.json          world-map points + country rollup
    <datatype>/<YYYYMMDD>/ingest.json       store-volume view of the day
    <datatype>/dates.json                   date index for the picker
"""

from __future__ import annotations

import json
import pathlib

import numpy as np
import pandas as pd

from onix.config import OnixConfig
from onix.oa.components import (GeoIPDB, build_reputation, domain_context,
                                reputation_column)
from onix.store import parse_date, results_path


def oa_dir(cfg: OnixConfig, datatype: str, date: str) -> pathlib.Path:
    y, mo, d = parse_date(date)
    return pathlib.Path(cfg.oa.data_dir) / datatype / f"{y}{mo}{d}"


def _load_geoip(cfg: OnixConfig) -> GeoIPDB:
    if cfg.oa.geoip_db:
        return GeoIPDB.load(cfg.oa.geoip_db)
    return GeoIPDB.builtin()


def _load_top_domains(cfg: OnixConfig) -> list[str]:
    """Popularity list, normalized to the SLD keys domain_context ranks
    by — accepts Alexa/Umbrella-style `google.com` lines, optional
    `rank,domain` CSV prefixes, or bare SLDs; first occurrence wins."""
    if not cfg.oa.top_domains:
        return []
    from onix.utils.features import subdomain_split
    out: list[str] = []
    seen = set()
    for line in pathlib.Path(cfg.oa.top_domains).read_text().splitlines():
        line = line.strip().lower()
        if not line or line.startswith("#"):
            continue
        name = line.rsplit(",", 1)[-1] if "," in line else line
        _, sld, _, _ = subdomain_split(name)
        if sld and sld not in seen:
            seen.add(sld)
            out.append(sld)
    return out


def _hours(df: pd.DataFrame, datatype: str) -> np.ndarray:
    """Hour-of-day per row, from the datatype's timestamp column."""
    if datatype == "flow":
        ts = pd.to_datetime(df["treceived"], format="mixed")
    elif datatype == "dns":
        ts = pd.to_datetime(df["frame_time"], format="mixed")
    else:
        ts = pd.to_datetime(df["p_time"], format="mixed")
    return ts.dt.hour.to_numpy(np.int32)


def enrich(df: pd.DataFrame, datatype: str, geo: GeoIPDB,
           rep_clients, top_domains: list[str]) -> pd.DataFrame:
    """Attach enrichment columns; df is the raw results CSV frame."""
    out = df.copy()
    if datatype == "flow":
        for col, prefix in (("sip", "src"), ("dip", "dst")):
            g = geo.lookup(out[col].astype(str))
            g.columns = [c.replace("geo_", f"{prefix}_geo_") for c in g.columns]
            out = pd.concat([out, g], axis=1)
        out["src_rep"] = reputation_column(rep_clients, out["sip"])
        out["dst_rep"] = reputation_column(rep_clients, out["dip"])
    elif datatype == "dns":
        g = geo.lookup(out["ip_dst"].astype(str))
        out = pd.concat([out, g], axis=1)
        dc = domain_context(out["dns_qry_name"].astype(str), top_domains)
        out = pd.concat([out, dc], axis=1)
        out["rep"] = reputation_column(rep_clients, out["dns_qry_name"])
    else:   # proxy
        g = geo.lookup(out["clientip"].astype(str))
        out = pd.concat([out, g], axis=1)
        dc = domain_context(out["host"].astype(str), top_domains)
        out = pd.concat([out, dc], axis=1)
        out["rep"] = reputation_column(rep_clients, out["host"])
    return out


def _graph(df: pd.DataFrame, datatype: str) -> dict:
    """Nodes + weighted links for the network/chord view."""
    if datatype == "flow":
        src, dst = df["sip"].astype(str), df["dip"].astype(str)
    elif datatype == "dns":
        src, dst = df["ip_dst"].astype(str), df["domain"].astype(str)
    else:
        src, dst = df["clientip"].astype(str), df["host"].astype(str)
    pairs = pd.DataFrame({"src": src, "dst": dst, "score": df["score"]})
    links = (pairs.groupby(["src", "dst"], sort=False)
             .agg(weight=("score", "size"), min_score=("score", "min"))
             .reset_index())
    nodes = sorted(set(links["src"]) | set(links["dst"]))
    return {
        "nodes": [{"id": n} for n in nodes],
        "links": [{"source": r.src, "target": r.dst,
                   "weight": int(r.weight),
                   "min_score": float(r.min_score)}
                  for r in links.itertuples()],
    }


def _human_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if n < 1024 or unit == "TB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024


# (actor column, peer column, peer noun) per datatype — the storyboard
# groups suspicious rows by the internal actor under investigation.
_STORY_KEYS = {
    "flow": ("sip", "dip", "external peer"),
    "dns": ("ip_dst", "domain", "queried domain"),
    "proxy": ("clientip", "host", "contacted host"),
}


def _storyboard(df: pd.DataFrame, datatype: str, top_n: int = 8) -> dict:
    """Per-actor threat cards — the reference's threat storyboard
    (README.md:45-48 "attack heuristics"/visual investigation) rebuilt
    as data: who, how many suspicious events, to which peers, when,
    how much moved, with a generated plain-language narrative. The
    `ranks` list ties each card back to its table rows for drill-down."""
    if not len(df):
        return {"threats": []}
    actor_col, peer_col, peer_noun = _STORY_KEYS[datatype]
    actors = df[actor_col].astype(str)
    hours = _hours(df, datatype)
    threats = []
    # Rank actors by how suspicious their worst event is, tie-broken by
    # volume — a single catastrophic connection outranks broad noise.
    order = (df.assign(_a=actors)
             .groupby("_a")["score"].agg(["min", "size"])
             .sort_values(["min", "size"], ascending=[True, False]))
    for actor in order.head(top_n).index:
        m = (actors == actor).to_numpy()
        rows = df[m]
        peers = rows[peer_col].astype(str).value_counts()
        hh = np.bincount(hours[m], minlength=24)[:24]
        active = np.flatnonzero(hh)
        t0, t1 = (int(active[0]), int(active[-1])) if len(active) else (0, 0)
        card = {
            "entity": actor,
            "n_events": int(m.sum()),
            "score_min": float(rows["score"].min()),
            "n_peers": int(peers.size),
            "peers": [{"id": k, "count": int(v)}
                      for k, v in peers.head(5).items()],
            "hourly": hh.tolist(),
            "ranks": rows["rank"].astype(int).tolist(),
        }
        story = (f"{actor} produced {card['n_events']} suspicious "
                 f"event{'s' if card['n_events'] != 1 else ''} across "
                 f"{card['n_peers']} {peer_noun}"
                 f"{'s' if card['n_peers'] != 1 else ''} between "
                 f"{t0:02d}:00 and {t1:02d}:59")
        if datatype == "flow" and "ibyt" in rows:
            total = float(rows["ibyt"].sum())
            card["bytes_total"] = total
            story += f", moving {_human_bytes(total)}"
        rep_cols = [c for c in ("dst_rep", "rep") if c in rows]
        flagged = 0
        if rep_cols:
            flagged = int((rows[rep_cols[0]].astype(str)
                           .isin(("HIGH", "MEDIUM"))).sum())
        if flagged:
            story += (f"; {flagged} hit{'s' if flagged != 1 else ''} on "
                      f"reputation-flagged destinations")
        card["story"] = story + "."
        threats.append(card)
    return {"threats": threats}


# Per-datatype (kind, geo column prefix, endpoint column) for the map
# view. Flow plots both ends of each connect; dns/proxy geolocate the
# client (the document/actor side — the only IP those rows carry).
_GEO_KINDS = {
    "flow": (("src", "src_geo_", "sip"), ("dst", "dst_geo_", "dip")),
    "dns": (("client", "geo_", "ip_dst"),),
    "proxy": (("client", "geo_", "clientip"),),
}


def _geo_points(df: pd.DataFrame, datatype: str,
                max_points: int = 2000) -> dict:
    """World-map data: one point per geolocatable endpoint of each
    suspicious row, plus a per-country rollup — the reference OA's
    globe/map view rebuilt on the enrichment columns
    (reference README.md:45-48 "Visualization"). Rows are already
    score-ascending, so capping at `max_points` keeps the most
    suspicious."""
    points: list[dict] = []
    country_count: dict[str, int] = {}
    country_min: dict[str, float] = {}
    for kind, prefix, id_col in _GEO_KINDS[datatype]:
        lat_c, lon_c, ctry_c = (f"{prefix}lat", f"{prefix}lon",
                                f"{prefix}country")
        if lat_c not in df.columns:
            continue
        lat = df[lat_c].to_numpy(float)
        lon = df[lon_c].to_numpy(float)
        ctry = df[ctry_c].astype(str).to_numpy()
        score = df["score"].to_numpy(float)
        rank = df["rank"].to_numpy()
        ids = df[id_col].astype(str).to_numpy()
        # (0,0)/unknown is the GeoIPDB miss value, not a real fix.
        ok = ~((lat == 0.0) & (lon == 0.0)) & (ctry != "unknown")
        for i in np.flatnonzero(ok):
            points.append({
                "lat": round(float(lat[i]), 3),
                "lon": round(float(lon[i]), 3),
                "rank": int(rank[i]), "score": float(score[i]),
                "kind": kind, "id": ids[i], "country": ctry[i],
            })
            c = ctry[i]
            country_count[c] = country_count.get(c, 0) + 1
            country_min[c] = min(country_min.get(c, np.inf),
                                 float(score[i]))
    # Cap AFTER collecting every kind: rank order across src+dst points
    # together, so at the cap the map keeps both endpoints of the most
    # suspicious rows rather than one kind's points exhausting the
    # budget.
    points.sort(key=lambda p: (p["rank"], p["kind"]))
    points = points[:max_points]
    countries = sorted(
        ({"country": c, "n": n, "min_score": country_min[c]}
         for c, n in country_count.items()),
        key=lambda r: -r["n"])
    return {"points": points, "countries": countries[:20],
            "n_located": int(sum(country_count.values()))}


# Timestamp column per datatype in the raw store partitions (the same
# columns _hours() bins for the suspicious rows).
_TS_COLS = {"flow": "treceived", "dns": "frame_time", "proxy": "p_time"}

# Above this many rows the per-hour histogram would mean scanning the
# whole day's timestamp column; the volume view then reports totals from
# parquet metadata only (row counts need no data pages).
_INGEST_HOURLY_CAP = 5_000_000


def _ingest_volumes(cfg: OnixConfig, datatype: str, date: str) -> dict:
    """Store-volume summary for the day: how much telemetry the day's
    partition actually holds, against which the suspicious count is
    read. The reference OA suite ships an ingest-summary page fed by
    the ingest pipeline's bookkeeping (SURVEY.md §2.1 #12); onix reads
    the truth directly from the store partition — parquet footer
    metadata for row counts, a timestamps-only column scan for the
    hourly profile when the day is small enough."""
    import pyarrow.parquet as pq

    from onix.store import Store

    pdir = Store(cfg.store.root).partition_dir(datatype, date)
    parts = Store.day_part_files(pdir)
    if not parts:
        return {"available": False, "rows_total": 0, "n_parts": 0,
                "bytes_total": 0, "hourly": None, "hourly_skipped": None}
    rows = 0
    nbytes = 0
    for p in parts:
        rows += pq.ParquetFile(p).metadata.num_rows
        nbytes += p.stat().st_size
    hourly = None
    hourly_skipped = None    # why hourly is null, for the dashboard
    ts_col = _TS_COLS[datatype]
    if rows > _INGEST_HOURLY_CAP:
        hourly_skipped = "too_large"
    else:
        try:
            ts = pd.concat([pd.read_parquet(p, columns=[ts_col])
                            for p in parts], ignore_index=True)
            hourly = np.bincount(_hours(ts, datatype),
                                 minlength=24)[:24].tolist()
        except (KeyError, ValueError):
            # partition predates the column; totals still stand
            hourly_skipped = "no_timestamps"
    return {"available": True, "rows_total": int(rows),
            "n_parts": len(parts), "bytes_total": int(nbytes),
            "hourly": hourly, "hourly_skipped": hourly_skipped}


def _summary(df: pd.DataFrame, datatype: str, date: str,
             manifest: dict | None) -> dict:
    scores = df["score"].to_numpy(np.float64)
    hist_counts, hist_edges = np.histogram(
        scores, bins=20) if len(scores) else (np.zeros(20, int),
                                              np.linspace(0, 1, 21))
    hours = _hours(df, datatype) if len(df) else np.zeros(0, np.int32)
    timeline = np.bincount(hours, minlength=24)[:24]
    doc_col = df["ip"].astype(str) if "ip" in df else pd.Series([], dtype=str)
    top_docs = doc_col.value_counts().head(10)
    out = {
        "datatype": datatype,
        "date": date,
        "n_results": int(len(df)),
        "score_min": float(scores.min()) if len(scores) else None,
        "score_max": float(scores.max()) if len(scores) else None,
        "histogram": {"counts": hist_counts.tolist(),
                      "edges": np.round(hist_edges, 6).tolist()},
        "timeline_hourly": timeline.tolist(),
        "top_documents": [{"ip": k, "count": int(v)}
                          for k, v in top_docs.items()],
    }
    if manifest:
        out["run"] = {k: manifest.get(k) for k in
                      ("n_events", "n_docs", "n_vocab", "n_tokens",
                       "engine", "config_hash", "seed", "wall_seconds",
                       "events_per_sec")}
        # Convergence series (SURVEY.md §5.5; ≙ lda-c's likelihood.dat):
        # the dashboard draws it so an analyst can see at a glance
        # whether the model behind today's ranking actually converged.
        ll = manifest.get("ll_history") or []
        out["run"]["ll_series"] = [round(float(v), 4) for _, v in ll]
    return out


def _update_dates_index(base: pathlib.Path, date: str) -> None:
    # flock the read-modify-write: two concurrent `onix oa` runs for
    # different dates of the same datatype must not drop each other's
    # entry from the picker index. The final write is tmp+rename so the
    # (lockless) HTTP GET path never observes a truncated file.
    from onix.oa.feedback import locked

    y, mo, d = parse_date(date)
    idx_path = base / "dates.json"
    with locked(idx_path):
        dates = set()
        if idx_path.exists():
            dates = set(json.loads(idx_path.read_text()))
        dates.add(f"{y}-{mo}-{d}")
        tmp = idx_path.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(sorted(dates)))
        tmp.replace(idx_path)


def run_oa(cfg: OnixConfig, date: str, datatype: str) -> int:
    res_csv = results_path(cfg.store.results_dir, datatype, date)
    if not res_csv.exists():
        print(f"onix oa: no results at {res_csv} — run `onix score` first")
        return 1
    df = pd.read_csv(res_csv)
    manifest = None
    man_path = res_csv.with_suffix(".manifest.json")
    if man_path.exists():
        manifest = json.loads(man_path.read_text())

    geo = _load_geoip(cfg)
    rep_clients = build_reputation(cfg.oa.reputation)
    top_domains = _load_top_domains(cfg)

    enriched = enrich(df, datatype, geo, rep_clients, top_domains)
    # Analyst columns: rank (1-based ascending by score — results CSV is
    # already score-ascending) and sev (0 = unlabeled; the scoring
    # notebook/label CLI writes 1/2 threat, 3 benign).
    enriched.insert(0, "rank", np.arange(1, len(enriched) + 1))
    enriched["sev"] = 0

    out = oa_dir(cfg, datatype, date)
    out.mkdir(parents=True, exist_ok=True)
    enriched.to_csv(out / "suspicious.csv", index=False)
    (out / "suspicious.json").write_text(
        enriched.to_json(orient="records"))
    summary = _summary(enriched, datatype, date, manifest)
    clients_csv = res_csv.with_name(res_csv.stem + "_clients.csv")
    if clients_csv.exists():
        cdf = pd.read_csv(clients_csv)
        summary["suspicious_clients"] = [
            {"client": str(r.client),
             "topic_rarity": float(r.topic_rarity),
             "n_tokens": int(r.n_tokens)}
            for r in cdf.head(20).itertuples()]
        cdf.to_csv(out / "clients.csv", index=False)
    (out / "summary.json").write_text(json.dumps(summary, indent=2))
    (out / "graph.json").write_text(json.dumps(_graph(enriched, datatype)))
    (out / "storyboard.json").write_text(
        json.dumps(_storyboard(enriched, datatype)))
    (out / "geo.json").write_text(
        json.dumps(_geo_points(enriched, datatype)))
    (out / "ingest.json").write_text(
        json.dumps(_ingest_volumes(cfg, datatype, date)))
    _update_dates_index(out.parent, date)
    print(f"onix oa: {len(enriched)} results -> {out}")
    return 0
