"""Analyst feedback capture — the noise-filter loop's write side.

The reference closes its human-in-the-loop cycle through in-dashboard
IPython scoring notebooks that write a feedback CSV the next ML run
consumes ×DUPFACTOR (SURVEY.md §2.1 #14, §3.3; reference README.md:48).
onix captures the same labels through the dashboard's label controls
(POSTed via `onix serve`) or the `onix label` CLI, and writes the CSV
`pipelines/run.load_feedback` reads: columns (ip, word, label) with the
reference severity scale 1/2 = threat, 3 = benign.
"""

from __future__ import annotations

import contextlib
import fcntl
import pathlib

import pandas as pd

from onix.config import OnixConfig
from onix.store import feedback_path

FEEDBACK_COLUMNS = ["ip", "word", "label", "rank", "score"]
VALID_LABELS = (1, 2, 3)        # 1 high threat, 2 medium, 3 benign


@contextlib.contextmanager
def locked(path: pathlib.Path):
    """Advisory exclusive lock on a sidecar file — serializes the
    read-modify-write across the threaded serve handlers AND a
    concurrently-running `onix label` process."""
    lock = path.with_suffix(path.suffix + ".lock")
    with open(lock, "w") as fh:
        fcntl.flock(fh, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(fh, fcntl.LOCK_UN)


def append_feedback(cfg: OnixConfig, datatype: str, date: str,
                    rows: pd.DataFrame) -> pathlib.Path:
    """Merge labeled rows into the day's feedback CSV.

    Rows need at least (ip, word, label); re-labeling the same (ip, word)
    keeps the newest label. Returns the feedback file path.
    """
    rows = rows.copy()
    missing = {"ip", "word", "label"} - set(rows.columns)
    if missing:
        raise ValueError(f"feedback rows missing columns {sorted(missing)}")
    numeric = pd.to_numeric(rows["label"], errors="raise")
    if not (numeric % 1 == 0).all():
        raise ValueError(f"labels must be integers, got {numeric.tolist()}")
    rows["label"] = numeric.astype(int)
    bad = set(rows["label"]) - set(VALID_LABELS)
    if bad:
        raise ValueError(f"labels must be in {VALID_LABELS}, got {sorted(bad)}")
    for col in FEEDBACK_COLUMNS:
        if col not in rows.columns:
            rows[col] = ""
    rows = rows[FEEDBACK_COLUMNS]

    path = feedback_path(cfg.store.feedback_dir, datatype, date)
    path.parent.mkdir(parents=True, exist_ok=True)
    with locked(path):
        if path.exists():
            old = pd.read_csv(path, dtype=str)
            rows = pd.concat([old, rows.astype(str)], ignore_index=True)
        rows = rows.astype(str).drop_duplicates(["ip", "word"], keep="last")
        rows.to_csv(path, index=False)
    return path


def label_by_rank(cfg: OnixConfig, datatype: str, date: str,
                  ranks: list[int], label: int) -> pathlib.Path:
    """Label OA results rows by their dashboard rank (1-based) — the
    `onix label` CLI path for headless analysts."""
    from onix.oa.engine import oa_dir
    sus = oa_dir(cfg, datatype, date) / "suspicious.csv"
    if not sus.exists():
        raise FileNotFoundError(
            f"no OA output at {sus} — run `onix oa {date} {datatype}` first")
    df = pd.read_csv(sus)
    sel = df[df["rank"].isin(ranks)]
    if len(sel) != len(set(ranks)):
        known = set(df["rank"].tolist())
        raise ValueError(f"unknown ranks: {sorted(set(ranks) - known)}")
    rows = sel[["ip", "word", "rank", "score"]].copy()
    rows["label"] = label
    return append_feedback(cfg, datatype, date, rows)
