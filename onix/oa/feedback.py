"""Analyst feedback capture — the noise-filter loop's write side.

The reference closes its human-in-the-loop cycle through in-dashboard
IPython scoring notebooks that write a feedback CSV the next ML run
consumes ×DUPFACTOR (SURVEY.md §2.1 #14, §3.3; reference README.md:48).
onix captures the same labels through the dashboard's label controls
(POSTed via `onix serve`) or the `onix label` CLI, and writes the CSV
`pipelines/run.load_feedback` reads: columns (ip, word, label) with the
reference severity scale 1/2 = threat, 3 = benign.
"""

from __future__ import annotations

import contextlib
import fcntl
import os
import pathlib
import uuid

import numpy as np
import pandas as pd

from onix.config import DATATYPES, OnixConfig
from onix.store import feedback_path, parse_date

# doc_id/word_id are OPTIONAL integer columns: the ids a /score client
# used, echoed back when labeling, which onix/feedback/filter.py
# compiles into the serving noise filter (rows without them still feed
# the ×DUPFACTOR corpus path and the streaming apply_feedback path).
FEEDBACK_COLUMNS = ["ip", "word", "label", "rank", "score",
                    "doc_id", "word_id"]
VALID_LABELS = (1, 2, 3)        # 1 high threat, 2 medium, 3 benign


@contextlib.contextmanager
def locked(path: pathlib.Path):
    """Advisory exclusive lock on a sidecar file — serializes the
    read-modify-write across the threaded serve handlers AND a
    concurrently-running `onix label` process."""
    lock = path.with_suffix(path.suffix + ".lock")
    with open(lock, "w") as fh:
        fcntl.flock(fh, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(fh, fcntl.LOCK_UN)


def _require_int_column(rows: pd.DataFrame, col: str, minimum: int,
                        what: str) -> None:
    """Validate an optional numeric column: present values (non-empty,
    non-NaN) must be integers >= minimum. Poisoned inputs here come
    straight off the wire (the /feedback POST) and silently bad ids
    would compile into a noise filter that suppresses the wrong
    events."""
    if col not in rows.columns:
        return
    raw = rows[col].replace("", None)
    present = raw.notna()
    if not present.any():
        return
    numeric = pd.to_numeric(raw[present], errors="coerce")
    if numeric.isna().any() or not (numeric % 1 == 0).all():
        raise ValueError(f"{what} must be integers, got "
                         f"{raw[present].tolist()}")
    if (numeric < minimum).any():
        raise ValueError(f"{what} must be >= {minimum}, got "
                         f"{numeric.tolist()}")


def append_feedback(cfg: OnixConfig, datatype: str, date: str,
                    rows: pd.DataFrame) -> pathlib.Path:
    """Merge labeled rows into the day's feedback CSV.

    Rows need at least (ip, word, label); re-labeling the same (ip, word)
    keeps the newest label. Returns the feedback file path.

    Crash-safety: the merged CSV is written to a unique temp file and
    renamed over the target INSIDE the advisory lock — a writer killed
    mid-write leaves the previous complete file, never a truncated one
    (the old in-place `to_csv` could tear the file under a crash, and
    every later reader — load_feedback, the filter compile — would
    then lose ALL prior labels). Concurrent appends from the threaded
    serve handlers and a separate `onix label` process serialize on
    `locked()` as before; the two-writer test exercises both
    processes racing."""
    if datatype not in DATATYPES:
        raise ValueError(f"datatype must be one of {DATATYPES}, "
                         f"got {datatype!r}")
    parse_date(date)                    # raises on malformed dates
    rows = rows.copy()
    missing = {"ip", "word", "label"} - set(rows.columns)
    if missing:
        raise ValueError(f"feedback rows missing columns {sorted(missing)}")
    numeric = pd.to_numeric(rows["label"], errors="raise")
    if not (numeric % 1 == 0).all():
        raise ValueError(f"labels must be integers, got {numeric.tolist()}")
    rows["label"] = numeric.astype(int)
    bad = set(rows["label"]) - set(VALID_LABELS)
    if bad:
        raise ValueError(f"labels must be in {VALID_LABELS}, got {sorted(bad)}")
    _require_int_column(rows, "rank", 1, "ranks")
    _require_int_column(rows, "doc_id", 0, "doc ids")
    _require_int_column(rows, "word_id", 0, "word ids")
    for col in ("rank", "doc_id", "word_id"):
        # Normalize validated int columns to int-or-empty STRINGS now:
        # a partially-filled numeric column is float dtype (NaN holes),
        # and a later astype(str) would write literal "nan"/"5.0"
        # cells into the CSV.
        if col in rows.columns:
            num = pd.to_numeric(rows[col].replace("", None),
                                errors="coerce")
            rows[col] = np.where(num.notna(),
                                 num.fillna(0).astype("int64").astype(str),
                                 "")
    for col in FEEDBACK_COLUMNS:
        if col not in rows.columns:
            rows[col] = ""
    rows = rows[FEEDBACK_COLUMNS]

    path = feedback_path(cfg.store.feedback_dir, datatype, date)
    path.parent.mkdir(parents=True, exist_ok=True)
    with locked(path):
        if path.exists():
            old = pd.read_csv(path, dtype=str)
            for col in FEEDBACK_COLUMNS:    # pre-r13 CSVs lack id cols
                if col not in old.columns:
                    old[col] = ""
            rows = pd.concat([old[FEEDBACK_COLUMNS],
                              rows.fillna("").astype(str)],
                             ignore_index=True)
        rows = rows.fillna("").astype(str) \
            .drop_duplicates(["ip", "word"], keep="last")
        tmp = path.with_name(f".fb-{os.getpid()}-{uuid.uuid4().hex[:8]}.tmp")
        rows.to_csv(tmp, index=False)
        tmp.replace(path)
    return path


def label_by_rank(cfg: OnixConfig, datatype: str, date: str,
                  ranks: list[int], label: int) -> pathlib.Path:
    """Label OA results rows by their dashboard rank (1-based) — the
    `onix label` CLI path for headless analysts."""
    from onix.oa.engine import oa_dir
    sus = oa_dir(cfg, datatype, date) / "suspicious.csv"
    if not sus.exists():
        raise FileNotFoundError(
            f"no OA output at {sus} — run `onix oa {date} {datatype}` first")
    df = pd.read_csv(sus)
    sel = df[df["rank"].isin(ranks)]
    if len(sel) != len(set(ranks)):
        known = set(df["rank"].tolist())
        raise ValueError(f"unknown ranks: {sorted(set(ranks) - known)}")
    rows = sel[["ip", "word", "rank", "score"]].copy()
    rows["label"] = label
    return append_feedback(cfg, datatype, date, rows)
