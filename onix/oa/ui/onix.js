/* onix analyst dashboard — shared renderer for flow/dns/proxy.
 *
 * Data contract (written by `onix oa`, served by `onix serve`):
 *   /data/<type>/dates.json                      available dates
 *   /data/<type>/<yyyymmdd>/suspicious.json      scored+enriched rows
 *   /data/<type>/<yyyymmdd>/summary.json         tiles/histogram/timeline
 *   /data/<type>/<yyyymmdd>/graph.json           network nodes+links
 * Labels POST to /feedback as {datatype, date, rows:[{ip,word,rank,score,label}]}.
 * Date routing uses the #date=YYYY-MM-DD hash like the reference UI.
 */
"use strict";

const TYPE = window.ONIX_TYPE;
const COLS = {
  flow: ["rank", "score", "treceived", "sip", "dip", "sport", "dport",
         "proto", "ipkt", "ibyt", "src_geo_country", "dst_geo_country",
         "dst_rep"],
  dns: ["rank", "score", "frame_time", "ip_dst", "dns_qry_name", "domain",
        "name_entropy", "dns_qry_type", "dns_qry_rcode", "geo_country",
        "rep"],
  proxy: ["rank", "score", "p_time", "clientip", "host", "reqmethod",
          "uripath", "respcode", "useragent", "geo_country", "rep"],
};
const REP_COLS = new Set(["rep", "src_rep", "dst_rep"]);
// Per-row event-time field (the same columns engine.py's summary uses).
const TIME_KEYS = { flow: "treceived", dns: "frame_time", proxy: "p_time" };
// Which row fields correspond to a graph edge's (source, target) — must
// match onix/oa/engine.py _graph().
const EDGE_KEYS = {
  flow: ["sip", "dip"],
  dns: ["ip_dst", "domain"],
  proxy: ["clientip", "host"],
};
const labels = new Map();   // rank -> label
let allRows = [];           // current date's suspicious rows
let currentDate = null;
let graphMode = "chord";    // "chord" | "list"
let lastGraph = null;
let tableSort = null;       // {col, dir} | null (null = rank order)
let tableFilter = "";       // substring filter over every rendered cell

function hashDate() {
  const m = location.hash.match(/date=(\d{4}-\d{2}-\d{2})/);
  return m ? m[1] : null;
}
function dayDir(date) { return date.replaceAll("-", ""); }
async function getJSON(url) {
  const r = await fetch(url);
  if (!r.ok) throw new Error(`${url}: ${r.status}`);
  return r.json();
}
function el(tag, attrs = {}, text = null) {
  const e = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs)) e.setAttribute(k, v);
  if (text !== null) e.textContent = text;
  return e;
}
function svgEl(tag, attrs = {}) {
  const e = document.createElementNS("http://www.w3.org/2000/svg", tag);
  for (const [k, v] of Object.entries(attrs)) e.setAttribute(k, v);
  return e;
}
function fmtScore(s) { return Number(s).toExponential(2); }


// Campaign suspects: document topic-rarity ranking (summary.json
// suspicious_clients, written by the round-5 scoring run). Event-level
// word rarity fades on sustained homogeneous campaigns; these are the
// clients whose token mass rides topics almost nobody else uses.
function renderClients(sum) {
  const list = sum.suspicious_clients || [];
  const panel = document.getElementById("clients-panel");
  if (!panel) return;
  if (!list.length) { panel.hidden = true; return; }
  panel.hidden = false;
  const tbl = el("table", { class: "mini" });
  const head = el("tr");
  ["#", "client", "topic rarity", "tokens"].forEach(
    h => head.append(el("th", {}, h)));
  tbl.append(head);
  list.forEach((c, i) => {
    const tr = el("tr", { class: "clickable" });
    tr.append(el("td", {}, String(i + 1)),
              el("td", {}, c.client),
              el("td", {}, Number(c.topic_rarity).toFixed(3)),
              el("td", {}, String(c.n_tokens)));
    tr.addEventListener("click", () => {
      // Results rows attribute each event to its achieving document
      // ("ip" column) — the same id space as the client ranking. An
      // ABSORBED campaign has no event-level rows by definition; say
      // so instead of presenting a silently empty drill.
      const mine = allRows.filter(r => String(r.ip) === c.client);
      openDrill(mine.length
        ? `client ${c.client}`
        : `client ${c.client} — no event-level hits (campaign ` +
          `absorbed into its own topic; evidence is the rarity ` +
          `score + clients.csv)`, mine);
    });
    tbl.append(tr);
  });
  document.getElementById("clients").replaceChildren(tbl);
}

function renderTiles(sum) {
  const run = sum.run || {};
  const tiles = [
    ["suspicious", sum.n_results],
    ["events scanned", run.n_events ?? "—"],
    ["documents (IPs)", run.n_docs ?? "—"],
    ["vocabulary", run.n_vocab ?? "—"],
    ["min score", sum.score_min == null ? "—" : fmtScore(sum.score_min)],
    ["events/sec", run.events_per_sec ?? "—"],
    ["run wall (s)", run.wall_seconds ?? "—"],
  ];
  const box = document.getElementById("tiles");
  box.replaceChildren(...tiles.map(([l, v]) => {
    const t = el("div", { class: "tile" });
    t.append(el("div", { class: "v" }, String(v)), el("div", { class: "l" }, l));
    return t;
  }));
  // Model-convergence tile: the per-sweep log-likelihood series from the
  // run manifest (the reference's likelihood.dat) as a sparkline, so a
  // non-converged model is visible right where the ranking is read.
  const ll = run.ll_series || [];
  if (ll.length >= 2) {
    const t = el("div", { class: "tile", title: "log-likelihood per sweep" });
    // sparkline() draws non-negative bar heights; log-likelihoods are
    // negative, so normalize the series into the 0.1..1 range — the
    // floor keeps a flat, already-converged series visibly non-empty.
    const lo = Math.min(...ll), hi = Math.max(...ll);
    const norm = ll.map(v => 0.1 + 0.9 * ((v - lo) / (hi - lo || 1)));
    const last = ll[ll.length - 1];
    t.append(sparkline(norm), el("div", { class: "l" },
                                 `convergence (final ll ${last.toFixed(3)})`));
    box.append(t);
  }
}

function renderBars(elId, values, titleFn) {
  const svgW = 460, svgH = 120, pad = 4;
  const box = document.getElementById(elId);
  const svg = svgEl("svg", { viewBox: `0 0 ${svgW} ${svgH}`, width: "100%" });
  const max = Math.max(1, ...values);
  const bw = (svgW - pad * 2) / values.length;
  values.forEach((v, i) => {
    const h = (svgH - 18) * v / max;
    const r = svgEl("rect", {
      class: "bar", x: pad + i * bw + 0.5, width: Math.max(bw - 1, 1),
      y: svgH - 14 - h, height: h,
    });
    r.append(svgEl("title"));
    r.querySelector("title").textContent = titleFn(i, v);
    svg.append(r);
  });
  const t0 = svgEl("text", { x: pad, y: svgH - 2 });
  t0.textContent = titleFn(0, values[0]).split(":")[0];
  const t1 = svgEl("text", { x: svgW - 60, y: svgH - 2 });
  t1.textContent = titleFn(values.length - 1, values.at(-1)).split(":")[0];
  svg.append(t0, t1);
  box.replaceChildren(svg);
}

function edgeTitle(l) {
  return `${l.source} → ${l.target} (${l.weight} events, ` +
    `min score ${fmtScore(l.min_score)})`;
}

function edgeOf(l, links, maxW) {
  // Shared edge decoration: hotness by score, width by weight, tooltip,
  // and the graph → rows drill-down on click.
  return {
    cls: "edge" + (l.min_score <= links[0].min_score * 10 ? " hot" : ""),
    width: Math.max(1, 4 * l.weight / maxW),
    attach(shape) {
      const t = svgEl("title");
      t.textContent = edgeTitle(l);
      shape.append(t);
      shape.addEventListener("click", () => showDrill(l));
    },
  };
}

function renderBipartite(links, box) {
  const srcs = [...new Set(links.map(l => l.source))];
  const dsts = [...new Set(links.map(l => l.target))];
  const rowH = 14, svgW = 460, pad = 110;
  const svgH = Math.max(srcs.length, dsts.length) * rowH + 24;
  const svg = svgEl("svg", { viewBox: `0 0 ${svgW} ${svgH}`, width: "100%" });
  const yOf = (list, id) => 16 + list.indexOf(id) * rowH;
  const maxW = Math.max(...links.map(l => l.weight));
  for (const l of links) {
    const deco = edgeOf(l, links, maxW);
    const line = svgEl("line", {
      class: deco.cls,
      x1: pad, y1: yOf(srcs, l.source),
      x2: svgW - pad, y2: yOf(dsts, l.target),
      "stroke-width": deco.width,
    });
    deco.attach(line);
    svg.append(line);
  }
  srcs.forEach(s => {
    const t = svgEl("text", { class: "node", x: pad - 6, y: yOf(srcs, s) + 3,
                              "text-anchor": "end" });
    t.textContent = s; svg.append(t);
  });
  dsts.forEach(d => {
    const t = svgEl("text", { class: "node", x: svgW - pad + 6,
                              y: yOf(dsts, d) + 3 });
    t.textContent = d; svg.append(t);
  });
  box.replaceChildren(svg);
}

function renderChord(links, box) {
  // Dependency-free chord-style view: every endpoint on a circle,
  // edges as quadratic curves pulled toward the center — the
  // reference's flow chord dashboard re-imagined without D3
  // (reference README.md:45-48,55-56).
  const ids = [...new Set(links.flatMap(l => [l.source, l.target]))];
  const svgW = 460, svgH = 460, cx = svgW / 2, cy = svgH / 2;
  const r = Math.min(cx, cy) - 76;
  const pos = new Map(ids.map((id, i) => {
    const a = (2 * Math.PI * i) / ids.length - Math.PI / 2;
    return [id, { x: cx + r * Math.cos(a), y: cy + r * Math.sin(a), a }];
  }));
  const svg = svgEl("svg", { viewBox: `0 0 ${svgW} ${svgH}`, width: "100%" });
  const maxW = Math.max(...links.map(l => l.weight));
  for (const l of links) {
    const p1 = pos.get(l.source), p2 = pos.get(l.target);
    const deco = edgeOf(l, links, maxW);
    const path = svgEl("path", {
      class: deco.cls, fill: "none",
      d: `M ${p1.x.toFixed(1)} ${p1.y.toFixed(1)} ` +
         `Q ${cx} ${cy} ${p2.x.toFixed(1)} ${p2.y.toFixed(1)}`,
      "stroke-width": deco.width,
    });
    deco.attach(path);
    svg.append(path);
  }
  for (const id of ids) {
    const p = pos.get(id);
    const deg = (p.a * 180) / Math.PI;
    const flip = deg > 90 || deg < -90;
    const t = svgEl("text", {
      class: "node",
      x: 0, y: 0,
      "text-anchor": flip ? "end" : "start",
      transform: `translate(${(cx + (r + 6) * Math.cos(p.a)).toFixed(1)},` +
        `${(cy + (r + 6) * Math.sin(p.a)).toFixed(1)}) ` +
        `rotate(${(flip ? deg + 180 : deg).toFixed(1)})`,
    });
    t.textContent = id;
    svg.append(t);
  }
  box.replaceChildren(svg);
}

function renderGraph(graph) {
  lastGraph = graph;
  const box = document.getElementById("graph");
  const links = [...graph.links].sort((a, b) => a.min_score - b.min_score)
    .slice(0, 60);
  if (!links.length) { box.replaceChildren(el("div", { class: "empty" }, "no edges")); return; }
  if (graphMode === "chord") renderChord(links, box);
  else renderBipartite(links, box);
  const btn = document.getElementById("graph-mode");
  btn.textContent = graphMode === "chord" ? "bipartite view" : "chord view";
  btn.onclick = () => {
    graphMode = graphMode === "chord" ? "list" : "chord";
    renderGraph(lastGraph);
  };
}

function openDrill(title, rows, { progression = false } = {}) {
  // Rows → label without touching the main table's ordering: render the
  // filtered rows in the drill panel with the same label controls
  // (shared `labels` map, same Save button). `progression: true` adds
  // the actor's incident-progression lanes (storyboard drills).
  document.getElementById("drill-title").textContent =
    `${title} — ${rows.length} suspicious row${rows.length === 1 ? "" : "s"}`;
  renderTable(rows, currentDate, document.getElementById("drill-table"));
  document.getElementById("drill-progression").replaceChildren();
  if (progression) renderProgression(rows);
  const panel = document.getElementById("drill-panel");
  panel.hidden = false;
  panel.scrollIntoView({ behavior: "smooth", block: "nearest" });
  document.getElementById("drill-clear").onclick = () => {
    panel.hidden = true;
  };
}

function showDrill(link) {
  const [ks, kt] = EDGE_KEYS[TYPE];
  const rows = allRows.filter(
    r => String(r[ks]) === String(link.source) &&
         String(r[kt]) === String(link.target));
  openDrill(`${link.source} → ${link.target}`, rows);
}

function hourFracOf(row) {
  // "2016-07-08 13:45:00" or "13:45:00" -> 13.75; null when unparsable.
  const m = String(row[TIME_KEYS[TYPE]] ?? "").match(/(\d{1,2}):(\d{2})/);
  return m ? Number(m[1]) + Number(m[2]) / 60 : null;
}

function renderEventTimeline(rows) {
  // Per-EVENT timeline (VERDICT r2 next #9): every suspicious row as a
  // dot at (time of day, score on a log axis). The hourly bars above
  // aggregate; this is the analyst's beacon-spotting view — periodic
  // dots in a horizontal line are a beacon, a burst is an exfil
  // window. Click a dot to open that event in the drill panel.
  const box = document.getElementById("event-timeline");
  const pts = rows.map(r => ({ r, h: hourFracOf(r), s: Number(r.score) }))
    .filter(p => p.h !== null && p.s > 0);
  if (!pts.length) {
    box.replaceChildren(el("div", { class: "empty" }, "no events"));
    return;
  }
  const svgW = 460, svgH = 150, padL = 34, padB = 16, padT = 6;
  const svg = svgEl("svg", { viewBox: `0 0 ${svgW} ${svgH}`, width: "100%" });
  const lo = Math.min(...pts.map(p => p.s)), hi = Math.max(...pts.map(p => p.s));
  const ll = Math.log(lo), lh = Math.log(hi * 1.0001);
  const yOf = s => padT + (svgH - padT - padB)
    * (1 - (Math.log(s) - ll) / (lh - ll || 1));
  const xOf = h => padL + (svgW - padL - 6) * h / 24;
  hourGrid(svg, xOf, padT, svgH - padB, svgH);
  [lo, hi].forEach(s => {
    const t = svgEl("text", { x: 1, y: yOf(s) + 3 });
    t.textContent = fmtScore(s);
    svg.append(t);
  });
  const hotCut = hotCutOf(pts);
  for (const p of pts) {
    const c = svgEl("circle", {
      class: "evt" + (p.s <= hotCut ? " hot" : ""),
      cx: xOf(p.h).toFixed(1), cy: yOf(p.s).toFixed(1), r: 2.5,
    });
    const t = svgEl("title");
    t.textContent = `rank ${p.r.rank} · score ${fmtScore(p.s)} · ` +
      `${p.r[TIME_KEYS[TYPE]]}`;
    c.append(t);
    c.addEventListener("click", () => openDrill(`event rank ${p.r.rank}`,
                                                [p.r]));
    svg.append(c);
  }
  box.replaceChildren(svg);
}

function fmtBytes(n) {
  for (const u of ["B", "KB", "MB", "GB", "TB"]) {
    if (n < 1024 || u === "TB") return `${n.toFixed(n < 10 ? 1 : 0)} ${u}`;
    n /= 1024;
  }
}

function renderGeo(geo) {
  // World-map view of the suspicious endpoints (the reference OA's
  // globe/map visualization re-rendered dependency-free): equirect
  // projection with a graticule, dot hotness = lowest-score decile,
  // click → that row in the drill panel. Beside it, the per-country
  // rollup as proportional bars.
  const box = document.getElementById("geo-map");
  const pts = (geo && geo.points) || [];
  if (!pts.length) {
    box.replaceChildren(el("div", { class: "empty" },
                           "no geolocatable endpoints"));
    document.getElementById("geo-countries").replaceChildren();
    return;
  }
  const svgW = 460, svgH = 240, padL = 26, padT = 6, padB = 14;
  const svg = svgEl("svg", { viewBox: `0 0 ${svgW} ${svgH}`, width: "100%" });
  const xOf = lon => padL + (svgW - padL - 6) * (lon + 180) / 360;
  const yOf = lat => padT + (svgH - padT - padB) * (90 - lat) / 180;
  for (let lon = -180; lon <= 180; lon += 60) {
    svg.append(svgEl("line", { class: "grid", x1: xOf(lon), x2: xOf(lon),
                               y1: yOf(90), y2: yOf(-90) }));
    const t = svgEl("text", { x: xOf(lon) - 10, y: svgH - 2 });
    t.textContent = `${lon}°`;
    svg.append(t);
  }
  for (let lat = -60; lat <= 60; lat += 30) {
    svg.append(svgEl("line", {
      class: "grid" + (lat === 0 ? " grid-eq" : ""),
      x1: xOf(-180), x2: xOf(180), y1: yOf(lat), y2: yOf(lat) }));
    const t = svgEl("text", { x: 1, y: yOf(lat) + 3 });
    t.textContent = `${lat}°`;
    svg.append(t);
  }
  const sorted = [...pts].sort((a, b) => a.score - b.score);
  const hotCut = sorted[Math.max(0, Math.floor(sorted.length / 10) - 1)].score;
  for (const p of pts) {
    const c = svgEl("circle", {
      class: "evt" + (p.score <= hotCut ? " hot" : ""),
      cx: xOf(p.lon).toFixed(1), cy: yOf(p.lat).toFixed(1), r: 3,
    });
    const t = svgEl("title");
    t.textContent = `${p.id} (${p.kind}) · ${p.country} · rank ${p.rank} · ` +
      `score ${fmtScore(p.score)}`;
    c.append(t);
    c.addEventListener("click", () => openDrill(
      `${p.id} (${p.country})`, allRows.filter(r => r.rank === p.rank)));
    svg.append(c);
  }
  box.replaceChildren(svg);
  const cbox = document.getElementById("geo-countries");
  const rows = (geo.countries || []).slice(0, 8);
  const maxN = Math.max(1, ...rows.map(r => r.n));
  cbox.replaceChildren(...rows.map(r => {
    const line = el("div", { class: "country-row" });
    const bar = el("div", { class: "country-bar" });
    bar.style.width = `${Math.max(2, 100 * r.n / maxN)}%`;
    line.append(
      el("span", { class: "country-name" }, r.country), bar,
      el("span", { class: "country-n",
                   title: `min score ${fmtScore(r.min_score)}` },
         String(r.n)));
    return line;
  }));
}

function renderIngest(ing, sum) {
  // Store-volume view of the day (the reference OA suite's
  // ingest-summary page): what the pipeline actually ingested, against
  // which the suspicious handful is read — README.md:42's "billion of
  // events to a few thousands" as a visible ratio.
  const tiles = document.getElementById("ingest-tiles");
  const hbox = document.getElementById("ingest-hourly");
  if (!ing || !ing.available) {
    tiles.replaceChildren(el("div", { class: "empty" },
                             "no store partition for this day"));
    hbox.replaceChildren();
    return;
  }
  const nSus = sum.n_results || 0;
  const ratio = nSus ? Math.round(ing.rows_total / nSus) : null;
  const cells = [
    ["events in store", ing.rows_total.toLocaleString()],
    ["part files", ing.n_parts],
    ["on disk", fmtBytes(ing.bytes_total)],
    ["filtered to", ratio ? `1 in ${ratio.toLocaleString()}` : "—"],
  ];
  tiles.replaceChildren(...cells.map(([l, v]) => {
    const t = el("div", { class: "tile" });
    t.append(el("div", { class: "v" }, String(v)),
             el("div", { class: "l" }, l));
    return t;
  }));
  if (ing.hourly && ing.hourly.some(v => v > 0)) {
    renderBars("ingest-hourly", ing.hourly,
      (i, v) => `${String(i).padStart(2, "0")}:00: ` +
        `${v.toLocaleString()} ingested`);
  } else {
    // hourly_skipped says WHY the engine left hourly null — a small
    // day without timestamps must not read as a volume problem.
    const why = ing.hourly_skipped === "too_large"
      ? "hourly profile skipped (day too large — totals from metadata)"
      : ing.hourly_skipped === "no_timestamps"
        ? "hourly profile unavailable (partition has no timestamp column)"
        : "no hourly profile";
    hbox.replaceChildren(el("div", { class: "empty" }, why));
  }
}

function sparkline(values, w = 120, h = 26) {
  const svg = svgEl("svg", { viewBox: `0 0 ${w} ${h}`, class: "spark" });
  const max = Math.max(1, ...values);
  const bw = w / values.length;
  values.forEach((v, i) => {
    const bh = (h - 2) * v / max;
    svg.append(svgEl("rect", {
      class: "bar", x: i * bw + 0.5, width: Math.max(bw - 1, 0.5),
      y: h - bh, height: bh,
    }));
  });
  return svg;
}

function hourGrid(svg, xOf, yTop, yBot, svgH) {
  // Shared 6-hour grid + HH:00 labels (event timeline + progression).
  for (let hh = 0; hh <= 24; hh += 6) {
    svg.append(svgEl("line", { class: "grid", x1: xOf(hh), x2: xOf(hh),
                               y1: yTop, y2: yBot }));
    const t = svgEl("text", { x: xOf(hh) - 8, y: svgH - 2 });
    t.textContent = `${String(hh).padStart(2, "0")}:00`;
    svg.append(t);
  }
}

function hotCutOf(pts) {
  // Lowest-score decile — the shared "most suspicious" emphasis.
  const sorted = [...pts].sort((a, b) => a.s - b.s);
  return sorted[Math.max(0, Math.floor(sorted.length / 10) - 1)].s;
}

function renderProgression(rows) {
  // Incident progression for one actor (the reference threat
  // investigation's progression tree, README.md:45-48): the actor's
  // suspicious events as time-ordered dots on one lane per peer,
  // most-suspicious peer first — beacon trains and lateral spread read
  // directly off the lanes. Rendered inside the drill panel when a
  // storyboard card opens.
  const box = document.getElementById("drill-progression");
  const [, kt] = EDGE_KEYS[TYPE];
  const pts = rows.map(r => ({ r, h: hourFracOf(r), peer: String(r[kt]),
                               s: Number(r.score) }))
    .filter(p => p.h !== null);
  if (pts.length < 2) { box.replaceChildren(); return; }
  const byPeer = new Map();
  for (const p of pts) {
    if (!byPeer.has(p.peer)) byPeer.set(p.peer, []);
    byPeer.get(p.peer).push(p);
  }
  const lanes = [...byPeer.entries()]
    .sort((a, b) => Math.min(...a[1].map(p => p.s))
                  - Math.min(...b[1].map(p => p.s)))
    .slice(0, 12);
  const rowH = 16, padL = 130, svgW = 460, padB = 14;
  const svgH = lanes.length * rowH + padB + 6;
  const svg = svgEl("svg", { viewBox: `0 0 ${svgW} ${svgH}`,
                             width: "100%", class: "progression" });
  const xOf = h => padL + (svgW - padL - 6) * h / 24;
  hourGrid(svg, xOf, 2, svgH - padB, svgH);
  const hotCut = hotCutOf(pts);
  lanes.forEach(([peer, ps], i) => {
    const y = 10 + i * rowH;
    const label = svgEl("text", { class: "node", x: padL - 6, y: y + 3,
                                  "text-anchor": "end" });
    label.textContent = peer;
    svg.append(label);
    const hs = ps.map(p => p.h);
    svg.append(svgEl("line", { class: "lane", y1: y, y2: y,
                               x1: xOf(Math.min(...hs)),
                               x2: xOf(Math.max(...hs)) }));
    for (const p of ps) {
      const c = svgEl("circle", {
        class: "evt" + (p.s <= hotCut ? " hot" : ""),
        cx: xOf(p.h).toFixed(1), cy: y, r: 3,
      });
      const t = svgEl("title");
      t.textContent = `${peer} · rank ${p.r.rank} · ` +
        `score ${fmtScore(p.s)} · ${p.r[TIME_KEYS[TYPE]]}`;
      c.append(t);
      svg.append(c);
    }
  });
  box.replaceChildren(svg);
}

function renderStoryboard(sb) {
  // The reference's threat storyboard (README.md:45-48) as cards: each
  // actor's narrative, activity sparkline, top peers; click → that
  // actor's rows in the drill panel for labeling.
  const box = document.getElementById("storyboard");
  const threats = (sb && sb.threats) || [];
  if (!threats.length) {
    box.replaceChildren(el("div", { class: "empty" }, "no threats"));
    return;
  }
  box.replaceChildren(...threats.map(t => {
    const card = el("div", { class: "story-card" });
    const head = el("div", { class: "story-head" });
    head.append(el("span", { class: "story-entity" }, t.entity),
                el("span", { class: "story-count" },
                   `${t.n_events} ev · min ${fmtScore(t.score_min)}`));
    const spark = sparkline(t.hourly || []);
    const story = el("div", { class: "story-text" }, t.story || "");
    const peers = el("div", { class: "story-peers" });
    (t.peers || []).forEach(p => peers.append(
      el("span", { class: "chip" }, `${p.id} ×${p.count}`)));
    card.append(head, spark, story, peers);
    card.addEventListener("click", () => {
      const set = new Set(t.ranks || []);
      openDrill(`threat ${t.entity}`,
                allRows.filter(r => set.has(r.rank)),
                { progression: true });
    });
    return card;
  }));
}

function viewRows(rows) {
  // Analyst table controls: substring filter over the rendered cells,
  // then column sort (numeric when both sides parse). Applied to the
  // MAIN table only — drill panels show their caller's exact rows.
  let out = rows;
  if (tableFilter) {
    const q = tableFilter.toLowerCase();
    const cols = COLS[TYPE];
    out = out.filter(r => cols.some(
      c => String(r[c] ?? "").toLowerCase().includes(q)));
  }
  if (tableSort) {
    const { col, dir } = tableSort;
    // ONE comparison mode for the whole column (numeric only when every
    // non-empty cell parses — a per-pair mode switch is intransitive and
    // makes Array.sort's result unspecified); empty cells always sort
    // last regardless of direction.
    const numeric = out.every(r => {
      const v = r[col];
      return v == null || v === "" || !Number.isNaN(Number(v));
    });
    out = [...out].sort((a, b) => {
      const x = a[col], y = b[col];
      const xm = x == null || x === "", ym = y == null || y === "";
      if (xm || ym) return xm && ym ? a.rank - b.rank : (xm ? 1 : -1);
      const cmp = numeric ? Number(x) - Number(y)
                          : String(x).localeCompare(String(y));
      return dir * cmp || a.rank - b.rank;
    });
  }
  return out;
}

function renderMainTable() {
  const shown = viewRows(allRows);
  const counter = document.getElementById("row-count");
  counter.textContent = shown.length === allRows.length
    ? `${allRows.length} rows`
    : `${shown.length} / ${allRows.length} rows`;
  renderTable(shown, currentDate);
}

function renderTable(rows, date, table = null) {
  const isMain = table === null;
  table = table || document.getElementById("sus-table");
  const cols = COLS[TYPE].filter(c => rows.length === 0 || c in rows[0]);
  const thead = el("thead");
  const hr = el("tr");
  cols.forEach(c => {
    const mark = (isMain && tableSort && tableSort.col === c)
      ? (tableSort.dir > 0 ? " ▲" : " ▼") : "";
    const th = el("th", isMain ? { class: "sortable" } : {}, c + mark);
    if (isMain) th.addEventListener("click", () => {
      tableSort = (tableSort && tableSort.col === c && tableSort.dir > 0)
        ? { col: c, dir: -1 }
        : (tableSort && tableSort.col === c) ? null : { col: c, dir: 1 };
      renderMainTable();
    });
    hr.append(th);
  });
  hr.append(el("th", {}, "sev"));
  thead.append(hr);
  const tbody = el("tbody");
  for (const row of rows) {
    const tr = el("tr");
    for (const c of cols) {
      const td = el("td", { class: c === "score" ? "score" : "" });
      let v = row[c];
      if (c === "score") v = fmtScore(v);
      td.textContent = v == null ? "" : v;
      if (REP_COLS.has(c)) td.className = `rep-${row[c]}`;
      td.title = row[c] == null ? "" : String(row[c]);
      tr.append(td);
    }
    const sel = el("select");
    [["0", "—"], ["1", "high"], ["2", "med"], ["3", "benign"]].forEach(
      ([v, t]) => sel.append(el("option", { value: v }, t)));
    sel.value = String(row.sev ?? 0);
    sel.addEventListener("change", () => {
      // Mutate the shared row object so the main table and a drill
      // panel rendering the same row stay consistent on re-render.
      row.sev = Number(sel.value);
      if (sel.value === "0") labels.delete(row.rank);
      else labels.set(row.rank, {
        ip: row.ip, word: row.word, rank: row.rank, score: row.score,
        label: Number(sel.value),
      });
      document.getElementById("save").disabled = labels.size === 0;
    });
    const labelTd = el("td");
    labelTd.append(sel);
    tr.append(labelTd);
    tbody.append(tr);
  }
  table.replaceChildren(thead, tbody);
  document.getElementById("save").onclick = async () => {
    const status = document.getElementById("status");
    try {
      const r = await fetch("/feedback", {
        method: "POST", headers: { "Content-Type": "application/json" },
        body: JSON.stringify({ datatype: TYPE, date,
                               rows: [...labels.values()] }),
      });
      const body = await r.json();
      if (!r.ok) throw new Error(body.error || r.status);
      status.textContent = `saved ${body.n} labels — consumed by the next run`;
      status.className = "ok";
      labels.clear();
      document.getElementById("save").disabled = true;
    } catch (e) {
      status.textContent = `save failed: ${e.message}`;
      status.className = "err";
    }
  };
}

async function load() {
  const dates = await getJSON(`/data/${TYPE}/dates.json`).catch(() => []);
  const picker = document.getElementById("date-picker");
  picker.replaceChildren(...dates.map(d => el("option", { value: d }, d)));
  const date = hashDate() || dates.at(-1);
  if (!date) {
    document.querySelector("main").replaceChildren(
      el("div", { class: "empty" },
         `no OA output for ${TYPE} yet — run \`onix oa <date> ${TYPE}\``));
    return;
  }
  picker.value = date;
  picker.onchange = () => { location.hash = `date=${picker.value}`; };
  const dir = `/data/${TYPE}/${dayDir(date)}`;
  const [rows, sum, graph, story, geo, ing] = await Promise.all([
    getJSON(`${dir}/suspicious.json`), getJSON(`${dir}/summary.json`),
    getJSON(`${dir}/graph.json`),
    getJSON(`${dir}/storyboard.json`).catch(() => ({ threats: [] })),
    getJSON(`${dir}/geo.json`).catch(() => ({ points: [], countries: [] })),
    getJSON(`${dir}/ingest.json`).catch(() => ({ available: false }))]);
  allRows = rows;
  currentDate = date;
  labels.clear();
  tableSort = null;
  tableFilter = "";
  const filt = document.getElementById("table-filter");
  filt.value = "";
  filt.oninput = () => { tableFilter = filt.value.trim(); renderMainTable(); };
  document.getElementById("save").disabled = true;
  document.getElementById("drill-panel").hidden = true;
  // Hosted notebooks for the current datatype (the reference hosts
  // investigation notebooks next to the dashboards): "notebook" opens
  // the server-rendered template, "run" executes it against this
  // day's data (POST /notebooks/run) and shows the live outputs, the
  // arrow downloads the .ipynb for a full Jupyter session.
  const nb = document.getElementById("notebook-link");
  nb.href = `/data/notebooks/${TYPE}_threat_investigation.ipynb`;
  nb.setAttribute("download", `${TYPE}_threat_investigation.ipynb`);
  document.getElementById("notebook-view").href = `/notebooks/${TYPE}.html`;
  // "edit" opens the in-dashboard editor: cells editable in place,
  // executed against a PERSISTENT kernel session (state carries
  // between runs), saved back to the hosted template.
  document.getElementById("notebook-edit").href =
    `/notebook.html?datatype=${TYPE}&date=${encodeURIComponent(date)}`;
  const nbRun = document.getElementById("notebook-run");
  let nbRunning = false;          // one kernel at a time per dashboard
  nbRun.onclick = async (ev) => {
    ev.preventDefault();
    if (nbRunning) return;
    nbRunning = true;
    nbRun.textContent = "⏳ running";
    // Open the tab NOW, inside the user activation — after a long
    // await, popup blockers would return null and discard the result.
    const w = window.open("", "_blank");
    if (w) w.document.write("<title>onix notebook</title>running…");
    try {
      const resp = await fetch("/notebooks/run", {
        method: "POST",
        headers: {"Content-Type": "application/json"},
        body: JSON.stringify({datatype: TYPE, date: currentDate}),
      });
      if (!resp.ok) throw new Error(`${resp.status} ${resp.statusText}`);
      const html = await resp.text();
      if (w) {
        w.document.open(); w.document.write(html); w.document.close();
      }
    } catch (e) {
      if (w) w.close();
      alert(`notebook run failed: ${e.message}`);
    } finally {
      nbRunning = false;
      nbRun.textContent = "▶ run";
    }
  };
  renderTiles(sum);
  renderBars("hist", sum.histogram.counts,
    (i, v) => `bin ${i}: ${v} events`);
  renderBars("timeline", sum.timeline_hourly,
    (i, v) => `${String(i).padStart(2, "0")}:00: ${v} events`);
  // Hour drill-down: a bar click opens that hour's suspicious rows.
  document.querySelectorAll("#timeline rect.bar").forEach((bar, hh) => {
    bar.classList.add("clickable");
    bar.addEventListener("click", () => {
      const rows = allRows.filter(r => Math.floor(hourFracOf(r) ?? -1) === hh);
      openDrill(`hour ${String(hh).padStart(2, "0")}:00`, rows);
    });
  });
  renderClients(sum);
  renderEventTimeline(rows);
  renderGraph(graph);
  renderStoryboard(story);
  renderGeo(geo);
  renderIngest(ing, sum);
  renderMainTable();
}

window.addEventListener("hashchange", load);
window.addEventListener("DOMContentLoaded", load);
