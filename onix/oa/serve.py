"""Dashboard web server — the notebook-file-server equivalent.

The reference serves its static UI from an IPython file server on port
8889 (`/files/ui/flow/suspicious.html#date=...`, reference
README.md:55-56). onix serves the same-shaped static UI from a stdlib
threading HTTP server, mounts the OA data dir at `/data/`, and accepts
the analyst's label POSTs at `/feedback` (the notebook write path of
SURVEY.md §2.1 #14, done with a button instead of a notebook cell).

No framework dependency on purpose: the UI is static files + JSON, the
only dynamic endpoint is the feedback write.
"""

from __future__ import annotations

import json
import math
import os
import pathlib
import threading
from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer

import pandas as pd

from onix.config import OnixConfig
from onix.oa.feedback import append_feedback

UI_ROOT = pathlib.Path(__file__).parent / "ui"
DEFAULT_PORT = 8889             # match the reference's demo port


def _safe_join(root: pathlib.Path, rel: str) -> pathlib.Path | None:
    """Resolve rel under root; None if it escapes (path traversal)."""
    target = (root / rel.lstrip("/")).resolve()
    root = root.resolve()
    if target == root or root in target.parents:
        return target
    return None


class OAHandler(SimpleHTTPRequestHandler):
    cfg: OnixConfig             # set on the subclass by make_server

    def log_message(self, fmt, *args):   # quiet by default
        pass

    def _send_file(self, path: pathlib.Path) -> None:
        if path.is_dir():
            path = path / "index.html"
        if not path.is_file():
            self.send_error(404)
            return
        data = path.read_bytes()
        self.send_response(200)
        self.send_header("Content-Type", self.guess_type(str(path)))
        self.send_header("Content-Length", str(len(data)))
        self.send_header("Cache-Control", "no-store")
        self.end_headers()
        self.wfile.write(data)

    def _resolve(self) -> pathlib.Path | None:
        path = self.path.split("?", 1)[0].split("#", 1)[0]
        if path.startswith("/data/"):
            root = pathlib.Path(self.cfg.oa.data_dir)
            return _safe_join(root, path[len("/data/"):])
        return _safe_join(UI_ROOT, path)

    def _notebook_or_reject(self, datatype: str) -> pathlib.Path | None:
        """Resolve a datatype to its installed template, sending the
        HTTP error itself when it can't — the allowlist (never the
        path) decides, and both the view and run endpoints share one
        ladder so the guidance cannot drift."""
        from onix.oa.notebooks import DATATYPES
        if datatype not in DATATYPES:
            self.send_error(404)
            return None
        nb = (pathlib.Path(self.cfg.oa.data_dir) / "notebooks"
              / f"{datatype}_threat_investigation.ipynb")
        if not nb.is_file():
            self.send_error(404, "notebook templates not installed "
                                 "(run `onix setup`)")
            return None
        return nb

    def _send_html(self, html: str, status: int = 200) -> None:
        data = html.encode()
        self.send_response(status)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.send_header("Cache-Control", "no-store")
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        path = self.path.split("?", 1)[0].split("#", 1)[0]
        if path == "/bank/stats":
            return self._bank_stats()
        if path == "/metrics":
            return self._metrics()
        # Editable notebook source (the in-dashboard editor's read
        # path): the installed per-datatype .ipynb as JSON.
        if path.startswith("/notebooks/") and path.endswith(".json"):
            nb = self._notebook_or_reject(
                path[len("/notebooks/"):-len(".json")])
            if nb is None:
                return
            try:
                self._send_json(200, json.loads(nb.read_text()))
            except (OSError, json.JSONDecodeError) as e:
                # Same contract as the .html route: a truncated
                # template is an HTTP 500, never a dropped connection.
                self.send_error(500, f"installed template unreadable: {e}")
            return
        # Hosted notebook view: the installed template rendered
        # server-side (no outputs; POST /notebooks/run executes it).
        if path.startswith("/notebooks/") and path.endswith(".html"):
            nb = self._notebook_or_reject(
                path[len("/notebooks/"):-len(".html")])
            if nb is None:
                return
            try:
                from onix.oa.notebooks import render_html
                html = render_html(nb)
            except ImportError as e:
                # nbformat/nbconvert are optional extras: a plain
                # install must get guidance, not a dropped connection.
                self.send_error(501, f"notebook rendering needs the "
                                     f"jupyter stack ({e.name}): pip "
                                     f"install nbconvert nbclient")
                return
            except Exception as e:              # noqa: BLE001 — e.g. a
                # truncated template: an HTTP 500, never a dropped
                # connection (same contract as /notebooks/run).
                self.send_error(500, f"notebook render failed: {e}")
                return
            self._send_html(html)
            return
        target = self._resolve()
        if target is None:
            self.send_error(403)
            return
        self._send_file(target)

    def do_HEAD(self):
        # Must mirror do_GET's root mapping — the inherited handler would
        # serve HEAD from the process cwd, bypassing _safe_join.
        target = self._resolve()
        if target is None:
            self.send_error(403)
            return
        if target.is_dir():
            target = target / "index.html"
        if not target.is_file():
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", self.guess_type(str(target)))
        self.send_header("Content-Length", str(target.stat().st_size))
        self.end_headers()

    def _reject_cross_site(self) -> bool:
        """CSRF guard for /feedback (model-poisoning vector: a benign
        label injected cross-site gets duplicated ×DUPFACTOR by the next
        run). The server binds localhost, but any web page the analyst
        visits can still fire a no-preflight POST at it — so require a
        same-origin Origin (when the browser sends one), a Host matching
        the bound address, and an application/json Content-Type (which
        forces a CORS preflight for cross-site senders)."""
        host = self.headers.get("Host", "")
        origin = self.headers.get("Origin")
        ctype = self.headers.get("Content-Type", "")
        if ctype.split(";", 1)[0].strip().lower() != "application/json":
            self.send_error(415, "Content-Type must be application/json")
            return True
        if origin is not None and origin != f"http://{host}":
            self.send_error(403, "cross-origin feedback rejected")
            return True
        # DNS rebinding needs an attacker-controlled DNS *name* resolving
        # to this server — so accept IP-literal Hosts (any bind address,
        # e.g. `onix serve --host 0.0.0.0` reached as http://10.1.2.3:8889)
        # and localhost/the bound name, reject other DNS names.
        hostname = host.rsplit(":", 1)[0] if ":" in host else host
        is_ip_literal = (hostname.startswith("[")          # IPv6
                         or hostname.replace(".", "").isdigit())
        if not is_ip_literal and hostname not in (
                "localhost", self.server.server_name):
            self.send_error(403, "unexpected Host header")
            return True
        return False

    def _reject_non_loopback(self) -> bool:
        """Code-executing endpoints (kernel exec, notebook save) are
        LOOPBACK-ONLY: the CSRF ladder deliberately accepts IP-literal
        Hosts so `--host 0.0.0.0` dashboards work across the network,
        but that must never extend to running code — any network peer
        could otherwise POST straight to the kernel. Feedback and the
        read-only routes keep the wider policy."""
        peer = self.client_address[0]
        if peer.startswith("127.") or peer in ("::1", "localhost"):
            return False
        self.send_error(
            403, "notebook editing/execution is loopback-only — open "
                 "the dashboard on the server host (ssh -L port "
                 "forwarding works) to use the editor")
        return True

    def _send_json(self, status: int, obj,
                   headers: dict | None = None) -> None:
        payload = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.send_header("Cache-Control", "no-store")
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def _read_json_body(self) -> dict:
        """Parse the request body; raises ValueError for anything that
        is not a JSON OBJECT (handlers translate to a 400 — malformed
        input must never drop the connection)."""
        try:
            n = int(self.headers.get("Content-Length", 0))
        except (TypeError, ValueError) as e:
            raise ValueError(f"bad Content-Length: {e}") from e
        body = json.loads(self.rfile.read(n))
        if not isinstance(body, dict):
            raise ValueError("request body must be a JSON object")
        return body

    def do_POST(self):
        path = self.path.split("?", 1)[0]
        if path == "/notebooks/run":
            return self._run_notebook()
        if path == "/notebooks/save":
            return self._save_notebook()
        if path == "/notebooks/kernel":
            return self._kernel_control()
        if path == "/notebooks/kernel/exec":
            return self._kernel_exec()
        if path == "/score":
            return self._score()
        if path != "/feedback":
            self.send_error(404)
            return
        if self._reject_cross_site():
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n))
            rows = pd.DataFrame(body["rows"])
            out = append_feedback(self.cfg, body["datatype"], body["date"],
                                  rows)
        except (KeyError, TypeError, ValueError, json.JSONDecodeError) as e:
            self.send_response(400)
            payload = json.dumps({"error": str(e)}).encode()
        else:
            # Close the loop LIVE (r13): recompile the tenant's noise
            # filter from the updated CSV and install it on an already-
            # running bank — set_filter bumps the model epoch, so every
            # cached winner set for this tenant is invalidated and the
            # very next /score re-scores under the filter. A server
            # with no bank yet loads the filter lazily on first score
            # (filter_loader below); either way dismissed winners never
            # outlive this POST.
            epoch = self._apply_feedback_filter(
                body["datatype"], body["date"], out)
            self.send_response(200)
            payload = json.dumps({"ok": True, "n": len(rows),
                                  "path": str(out),
                                  "model_epoch": epoch}).encode()
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _apply_feedback_filter(self, datatype: str, date: str,
                               csv_path) -> int | None:
        """Install the recompiled filter on the live bank service (if
        one exists); returns the tenant's new model epoch, or None when
        no bank is running yet."""
        if not self.cfg.feedback.filter_enabled:
            return None      # online-update-only configuration
        service = self.server.peek_bank_service()
        if service is None:
            return None
        from onix.feedback.filter import filter_from_csv
        from onix.store import model_name
        base = model_name(datatype, date)
        with service.lock:
            # Compile INSIDE the lock: an install always reflects the
            # CSV's state at install time and installs are serialized,
            # so two racing /feedback POSTs can never leave an older
            # snapshot as the live filter (the last installer has read
            # a CSV containing every append that preceded it).
            # apply_feedback_filter also reaches sub-tenants (which
            # share the per-(datatype, date) CSV) and drops cache
            # entries epochs cannot reach.
            filt = filter_from_csv(csv_path,
                                   self.cfg.feedback.boost_scale)
            return service.apply_feedback_filter(base, filt)


    # -- model-bank scoring (r12, onix/serving/) --------------------------
    #
    # The serving tentpole's HTTP face: mixed-tenant request batches
    # scored through the device-resident bank in ONE batched dispatch
    # per wave, with per-(tenant, window) winner caching. Tenants are
    # the fitted models under serving.models_dir (store.model_name
    # keys, persisted by run_scoring when serving.save_fitted is on).
    # Same cross-site guard as /feedback; scoring is read-only w.r.t.
    # models, so it keeps the wider (non-loopback) policy.

    def _score(self):
        # r18 telemetry: the trace id arrives on X-Request-Id (or is
        # minted here) and rides a contextvar through submit() -> the
        # admission queue wait -> the bank wave dispatch, so one slow
        # request decomposes into its named spans end-to-end. The id is
        # echoed back (header + body) for client-side correlation.
        from onix.utils import telemetry
        trace_id = self.headers.get("X-Request-Id") \
            or telemetry.new_trace_id()
        with telemetry.TRACER.trace(trace_id):
            with telemetry.TRACER.span("serve.request"):
                return self._score_traced(trace_id)

    def _score_traced(self, trace_id: str):
        if self._reject_cross_site():
            return
        from onix.serving.model_bank import BankRefusal, ScoreRequest
        from onix.utils.resilience import (Deadline, DeadlineExceeded,
                                           Overloaded)
        # The deadline clock starts at request RECEIPT — time spent in
        # the admission queue counts against the budget, so a request
        # that queued past its deadline is refused instead of burning
        # device time on an answer the client abandoned.
        deadline = (Deadline(self.cfg.serving.request_deadline_ms / 1e3)
                    if self.cfg.serving.request_deadline_ms > 0 else None)
        try:
            body = self._read_json_body()
            raw = body["requests"]
            if not (isinstance(raw, list) and raw):
                raise ValueError("requests must be a non-empty list")
            import numpy as np
            reqs = []
            for r in raw:
                if not isinstance(r, dict):
                    raise ValueError("each request must be an object")
                win = r.get("window")
                reqs.append(ScoreRequest(
                    tenant=str(r["tenant"]),
                    doc_ids=np.asarray(r["doc_ids"], np.int32),
                    word_ids=np.asarray(r["word_ids"], np.int32),
                    window=None if win is None else str(win)))
            tol = float(body.get("tol", self.cfg.pipeline.tol))
            max_results = int(body.get("max_results",
                                       self.cfg.pipeline.max_results))
            if not 1 <= max_results <= 100_000:
                raise ValueError(f"bad max_results {max_results}")
        except (KeyError, TypeError, ValueError,
                json.JSONDecodeError) as e:
            self.send_error(400, f"bad request: {e}")
            return
        from onix.checkpoint import ModelIntegrityError
        service = self.server.bank_service(self.cfg)
        try:
            # submit() is the r16 admission-controlled entry: it takes
            # the service's scoring lock itself (one writer at a time —
            # residency + cache bookkeeping are host-side state shared
            # across handler threads), sheds past max_queue_depth, and
            # refuses deadline-expired requests before any device work.
            results = service.submit(reqs, tol=tol,
                                     max_results=max_results,
                                     deadline=deadline)
        except Overloaded as e:
            # Load shed: 503 + Retry-After, nothing mutated
            # (docs/ROBUSTNESS.md "serving resilience"). RFC 9110
            # delay-seconds is a non-negative INTEGER — a fractional
            # value makes spec-compliant clients (urllib3 Retry) choke
            # on the header — so round the hint up to a whole second.
            # The trace id is echoed on EVERY outcome — refusals most
            # of all: a shed 503 is exactly the response the operator
            # wants to join against its serve-shed flight dump.
            self._send_json(503, {"ok": False, "shed": True,
                                  "trace_id": trace_id,
                                  "error": str(e)},
                            headers={"Retry-After":
                                     str(max(1, math.ceil(
                                         e.retry_after_s))),
                                     "X-Request-Id": trace_id})
            return
        except DeadlineExceeded as e:
            self._send_json(503, {"ok": False, "deadline_expired": True,
                                  "trace_id": trace_id,
                                  "error": str(e)},
                            headers={"Retry-After": "1",
                                     "X-Request-Id": trace_id})
            return
        except (BankRefusal, ModelIntegrityError) as e:
            # Refusal semantics (docs/ROBUSTNESS.md): unknown tenant,
            # out-of-range ids, rotted model — rejected before any
            # device work, never scored against wrong tables.
            self._send_json(404, {"ok": False, "trace_id": trace_id,
                                  "error": str(e)},
                            headers={"X-Request-Id": trace_id})
            return
        # Unfilled TopK slots (index -1) carry +inf scores; json.dumps
        # would emit the non-standard token `Infinity` (invalid per RFC
        # 8259 — JSON.parse in a browser throws). Null them instead.
        self._send_json(200, {"ok": True, "trace_id": trace_id,
                              "results": [
            {"tenant": req.tenant, "window": req.window,
             "cached": res.cached, "degraded": res.degraded,
             "scores": [s if math.isfinite(s) else None
                        for s in np.asarray(res.topk.scores).tolist()],
             "indices": np.asarray(res.topk.indices).tolist()}
            for req, res in zip(reqs, results)]},
                        headers={"X-Request-Id": trace_id})

    def _bank_stats(self):
        from onix.checkpoint import list_models
        from onix.utils.obs import counters
        service = self.server.bank_service(self.cfg)
        front = getattr(service, "replicas", None)
        with service.lock:
            if front is not None:
                # Multi-replica front (r20): aggregate the per-replica
                # banks; `tiers` carries each replica's HBM / host-RAM
                # / disk occupancy + hit/prefetch accounting.
                banks = [s.bank for s in front]
                stats = {
                    "tenants_registered": sum(len(b.tenants())
                                              for b in banks),
                    "dispatches": sum(b.dispatches for b in banks),
                    "compiled_shapes": sum(len(b.compiled_shapes)
                                           for b in banks),
                    "tiers": service.tier_stats(),
                }
            else:
                stats = {
                    "tenants_registered": len(service.bank.tenants()),
                    "dispatches": service.bank.dispatches,
                    "compiled_shapes": len(service.bank.compiled_shapes),
                    # r20 residency tiers: HBM (device-resident), host
                    # RAM (registry / prefetcher), disk (loader) —
                    # occupancy, hit/miss, and prefetch counters.
                    "tiers": service.bank.tier_stats(),
                }
            stats.update({
                "models_on_disk": len(list_models(
                    self.cfg.serving.models_dir)),
                "cache": service.cache_stats(),
                "admission": service.admission_stats(),
                "counters": {**counters.snapshot("bank"),
                             **counters.snapshot("serve")},
            })
        self._send_json(200, stats)

    def _metrics(self):
        """GET /metrics — Prometheus text exposition (r18,
        docs/OBSERVABILITY.md): every counter, every latency histogram
        (span durations, log-bucketed), admission/queue gauges, bank
        residency + epoch stats, and the build/config identity. Same
        posture as /bank/stats (plain GET on the bound address; no
        state changes, no code execution). Deadline-bounded: bank
        internals are read under a 250 ms lock attempt — a scrape
        landing mid-wave reports `onix_metrics_partial 1` instead of
        stalling behind device work, and never instantiates the bank
        on a dashboards-only server."""
        from onix.utils import telemetry
        from onix.utils.obs import counters
        gauges: dict[str, float] = {
            "telemetry.enabled": 1.0 if telemetry.TRACER.enabled else 0.0,
            "telemetry.sample": telemetry.TRACER.sample,
        }
        service = self.server.peek_bank_service()
        if service is not None:
            adm = service.admission_stats()     # _admit_lock only
            gauges["serve.queue_depth"] = adm["queue_depth"]
            gauges["serve.queue_depth_high_water"] = adm["queue_depth_peak"]
            gauges["serve.max_queue_depth"] = adm["max_queue_depth"]
            # Multi-replica front (r20): walk each live replica's
            # service; the single-service path is the same loop over
            # one element. Gauges aggregate (sums; epoch max).
            front = getattr(service, "replicas", None)
            if front is not None:
                services = [front[i] for i in service.alive_indices()]
                gauges["serve.replicas_alive"] = len(services)
                gauges["serve.replicas_down"] = \
                    len(front) - len(services)
            else:
                services = [service]
            agg: dict[str, float] = {}
            epoch_max = 0
            covered = 0
            for svc in services:
                # Each replica's bank internals live under ITS lock
                # (one lock == the whole service pre-r20); a scrape
                # landing mid-wave on one replica reports partial
                # instead of stalling behind that replica's device
                # work.
                if not svc.lock.acquire(timeout=0.25):
                    continue
                try:
                    bank = svc.bank
                    epochs = list(bank._epochs.values())
                    epoch_max = max([epoch_max] + epochs)
                    tiers = bank.tier_stats()
                    for k, v in {
                        "bank.tenants_registered": len(bank.tenants()),
                        "bank.tenants_resident": sum(
                            len(sh.lru)
                            for sh in bank._shards.values()),
                        "bank.shape_classes": len(bank._shards),
                        "bank.compiled_shape_count":
                            len(bank.compiled_shapes),
                        "bank.dispatch_count": bank.dispatches,
                        "bank.tenants_with_filters": len(bank._filters),
                        "bank.winner_cache_entries": len(svc._cache),
                        # r20 residency tiers: live occupancy per tier
                        # (the counters carry hit/miss rates).
                        "bank.tier_hbm_resident":
                            tiers["hbm"]["resident"],
                        "bank.tier_host_resident":
                            tiers["host"]["resident"],
                        "bank.prefetch_tracked_tenants":
                            tiers["prefetch"]["tracked_tenants"],
                    }.items():
                        agg[k] = agg.get(k, 0) + v
                    covered += 1
                finally:
                    svc.lock.release()
            if covered:
                gauges.update(agg)
                gauges["bank.model_epoch_max"] = epoch_max
            if covered < len(services):
                gauges["metrics.partial"] = 1.0
        body = telemetry.render_prometheus(
            counters.snapshot(), telemetry.histograms, gauges,
            info={"config_hash": self.cfg.config_hash,
                  "store_root": self.cfg.store.root})
        data = body.encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.send_header("Cache-Control", "no-store")
        self.end_headers()
        self.wfile.write(data)

    def _run_notebook(self):
        """Execute the datatype's investigation notebook against the
        live OA data dir and return the rendered HTML — the hosted-
        notebook path (reference README.md:55: notebooks live next to
        the dashboards). Same cross-site guard as /feedback: execution
        is code-running state, never reachable from another origin."""
        if self._reject_cross_site():
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n))
            datatype = str(body["datatype"])
            date = str(body["date"])
        except (KeyError, TypeError, ValueError, json.JSONDecodeError) as e:
            self.send_error(400, f"bad request: {e}")
            return
        nb = self._notebook_or_reject(datatype)
        if nb is None:
            return
        from onix.oa.notebooks import execute_to_html

        # The kernel is a fresh interpreter: hand it the RESOLVED
        # config (not a maybe-stale file path) so the notebook reads
        # the exact data dir this server serves.
        import tempfile
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump(self.cfg.to_dict(), f)
            cfg_path = f.name
        try:
            html = execute_to_html(nb, date=date, config_path=cfg_path)
        except ImportError as e:
            # notebooks.py imports the jupyter stack lazily inside the
            # call — a plain install gets guidance, not a dropped
            # connection.
            self.send_error(501, f"notebook execution needs the jupyter "
                                 f"stack ({e.name}): pip install "
                                 f"nbconvert nbclient")
            return
        except Exception as e:                  # noqa: BLE001 — kernel spawn
            self.send_error(500, f"notebook execution failed: {e}")
            return
        finally:
            import os
            os.unlink(cfg_path)
        self._send_html(html)


    # -- interactive notebooks (VERDICT r03 missing #3) -------------------
    #
    # The reference's dashboards ARE a live notebook server: the analyst
    # edits cells in place and re-runs them against a persistent kernel.
    # These endpoints supply that loop natively: save writes the
    # installed .ipynb (the same file /notebooks/<dt>.html renders and
    # the ⤓ download serves), kernel start/exec run cells statefully in
    # a supervised worker process (onix/oa/kernel.py). All POSTs share
    # the /feedback cross-site guard — cell execution is code-running
    # state and must never be reachable from another origin.

    def _save_notebook(self):
        if self._reject_cross_site() or self._reject_non_loopback():
            return
        try:
            body = self._read_json_body()
            datatype = str(body["datatype"])
            cells = body["cells"]
            if not (isinstance(cells, list) and cells):
                raise ValueError("cells must be a non-empty list")
            for c in cells:
                if not isinstance(c, dict):
                    raise ValueError("each cell must be an object")
                if c.get("cell_type") not in ("code", "markdown"):
                    raise ValueError(
                        f"bad cell_type {c.get('cell_type')!r}")
                if not isinstance(c.get("source"), str):
                    raise ValueError("source must be a string")
        except (KeyError, TypeError, ValueError,
                json.JSONDecodeError) as e:
            self.send_error(400, f"bad request: {e}")
            return
        nb_path = self._notebook_or_reject(datatype)
        if nb_path is None:
            return
        try:
            nb = json.loads(nb_path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            self.send_error(500, f"installed template unreadable: {e}")
            return
        nb["cells"] = [{
            "cell_type": c["cell_type"],
            "id": f"onix-{datatype}-{i}",
            "metadata": {},
            "source": c["source"].splitlines(keepends=True),
            **({"outputs": [], "execution_count": None}
               if c["cell_type"] == "code" else {}),
        } for i, c in enumerate(cells)]
        # Unique temp + atomic replace: two tabs saving concurrently
        # must each publish a complete file (same pattern as
        # Store.append).
        import uuid
        tmp = nb_path.with_name(f".save-{uuid.uuid4().hex[:8]}.tmp")
        tmp.write_text(json.dumps(nb, indent=1))
        tmp.replace(nb_path)
        self._send_json(200, {"ok": True, "n_cells": len(cells)})

    def _kernel_env(self, date: str) -> tuple[dict, str]:
        import tempfile
        fd, cfg_path = tempfile.mkstemp(prefix="onix-kernel-",
                                        suffix=".json")
        with os.fdopen(fd, "w") as f:
            json.dump(self.cfg.to_dict(), f)
        return {"ONIX_DATE": date, "ONIX_CONFIG": cfg_path}, cfg_path

    def _kernel_control(self):
        if self._reject_cross_site() or self._reject_non_loopback():
            return
        try:
            body = self._read_json_body()
            action = str(body.get("action", "start"))
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            self.send_error(400, f"bad request: {e}")
            return
        km = self.server.kernels
        if action == "start":
            date = str(body.get("date", ""))
            env, cfg_path = self._kernel_env(date)
            s = km.start(env=env, cleanup_files=[cfg_path])
            self._send_json(200, {"ok": True, "session": s.id})
            return
        if action == "stop":
            ok = km.stop(str(body.get("session", "")))
            self._send_json(200, {"ok": ok})
            return
        self.send_error(400, f"unknown action {action!r}")

    def _kernel_exec(self):
        if self._reject_cross_site() or self._reject_non_loopback():
            return
        from onix.oa.kernel import KernelDead
        try:
            body = self._read_json_body()
            sid = str(body["session"])
            code = str(body["code"])
        except (KeyError, TypeError, ValueError,
                json.JSONDecodeError) as e:
            self.send_error(400, f"bad request: {e}")
            return
        s = self.server.kernels.get(sid)
        if s is None:
            self._send_json(410, {"ok": False,
                                  "error": "no such kernel session "
                                           "(start a new one)"})
            return
        try:
            timeout = float(self.cfg.oa.kernel_cell_timeout_s)
            resp = s.execute(code, timeout=timeout)
        except KernelDead as e:
            self.server.kernels.drop(sid)
            self._send_json(410, {"ok": False, "error": str(e)})
            return
        self._send_json(200, resp)


class OAServer(ThreadingHTTPServer):
    """ThreadingHTTPServer + the kernel-session registry (one per
    server, shared across handler threads)."""

    def __init__(self, *args, **kw):
        from onix.oa.kernel import KernelManager
        super().__init__(*args, **kw)
        self.kernels = KernelManager()
        # Guards LAZY CONSTRUCTION of the bank service only (r16):
        # scoring + filter installs serialize on the service's OWN
        # lock (BankService.lock), which submit() takes itself after
        # admission control — so a shed request never waits here.
        self.bank_lock = threading.Lock()
        self._bank_service = None

    def peek_bank_service(self):
        """The bank service if one has been created — the /feedback
        handler must never instantiate jax + the bank just to record a
        label on a dashboards-only server."""
        return self._bank_service

    def bank_service(self, cfg: OnixConfig):
        """The per-server BankService, created on first /score — jax
        and the bank arrays never load for a dashboards-only server.
        The loader pulls fitted models from serving.models_dir on
        first reference (checkpoint.load_model — digest-verified)."""
        with self.bank_lock:
            if self._bank_service is None:
                from onix.checkpoint import load_models
                from onix.serving.model_bank import (BankRefusal,
                                                     BankService, ModelBank,
                                                     TenantModel)

                def _as_tenant_model(name: str, m) -> TenantModel:
                    if m.arrays["theta"].ndim != 2:
                        raise BankRefusal(
                            f"model {name!r} is multi-chain "
                            f"({m.arrays['theta'].shape}); combine "
                            "chains upstream before banking")
                    return TenantModel(
                        m.arrays["theta"], m.arrays["phi_wk"],
                        epoch=int(m.meta.get("model_epoch", 0)))

                def bulk_loader(names: list[str]) -> dict[str, TenantModel]:
                    # ONE host-side pass over the misses
                    # (checkpoint.load_models); absent names simply
                    # missing from the result -> BankRefusal upstream.
                    try:
                        loaded = load_models(cfg.serving.models_dir, names)
                    except ValueError as e:     # path traversal attempt
                        raise BankRefusal(str(e)) from e
                    return {name: _as_tenant_model(name, m)
                            for name, m in loaded.items()}

                def loader(tenant: str) -> TenantModel | None:
                    return bulk_loader([tenant]).get(tenant)

                def filter_loader(tenant: str):
                    # Tenant names are store.model_name keys
                    # (<datatype>/<yyyymmdd>[/<sub>]): the persisted
                    # feedback CSV for that (datatype, date) compiles
                    # into the tenant's noise filter on first load —
                    # a restarted server keeps suppressing what the
                    # analyst already dismissed.
                    if not cfg.feedback.filter_enabled:
                        return None
                    from onix.feedback.filter import filter_from_csv
                    from onix.store import feedback_path
                    parts = tenant.split("/")
                    if len(parts) < 2:
                        return None
                    try:
                        path = feedback_path(cfg.store.feedback_dir,
                                             parts[0], parts[1])
                    except ValueError:
                        return None
                    return filter_from_csv(path,
                                           cfg.feedback.boost_scale)

                def epoch_loader(tenant: str):
                    # One small json read: lets a live server adopt a
                    # re-save (re-fit, online nudge) from ANOTHER
                    # process — the epoch moves and the old tables
                    # drop before any cached winner can be served.
                    from onix.checkpoint import model_meta_epoch
                    try:
                        return model_meta_epoch(cfg.serving.models_dir,
                                                tenant)
                    except ValueError:      # traversal-shaped name
                        return None

                # r20 mesh placement: hand the bank the device list so
                # select_shard_form can resolve against a real mesh
                # (auto stays single-device until the queued TPU
                # crossover fills _BANK_SHARD_MIN_TENANTS).
                import jax

                def _one_service() -> BankService:
                    bank = ModelBank(
                        capacity=cfg.serving.bank_capacity,
                        form=cfg.serving.bank_form,
                        loader=loader, bulk_loader=bulk_loader,
                        host_capacity=cfg.serving.host_model_cache,
                        filter_loader=filter_loader,
                        epoch_loader=epoch_loader,
                        serve_form=cfg.serving.serve_form,
                        degrade_form_fallback=(
                            cfg.serving.degrade_form_fallback),
                        devices=jax.devices(),
                        shard_form=cfg.serving.bank_shard,
                        prefetch_depth=cfg.serving.prefetch_depth)
                    return BankService(
                        bank,
                        max_batch_requests=cfg.serving.max_batch_requests,
                        cache_size=cfg.serving.winner_cache_size,
                        max_queue_depth=cfg.serving.max_queue_depth,
                        request_deadline_s=(
                            cfg.serving.request_deadline_ms / 1e3))

                if cfg.serving.replicas > 1:
                    # N replicas behind one front: each replica owns
                    # its own bank + winner cache; the front routes by
                    # tenant hash and propagates epoch bumps
                    # (onix/serving/replicas.py). All replicas share
                    # this process's model store, so the r13
                    # refresh_from_disk probe works unchanged per
                    # replica.
                    from onix.serving.replicas import ReplicaFront
                    self._bank_service = ReplicaFront(
                        [_one_service()
                         for _ in range(cfg.serving.replicas)])
                else:
                    self._bank_service = _one_service()
            return self._bank_service

    def server_close(self):
        self.kernels.close_all()
        super().server_close()


def make_server(cfg: OnixConfig, port: int = DEFAULT_PORT,
                host: str = "127.0.0.1") -> ThreadingHTTPServer:
    # The server is where the resolved config meets the process-global
    # telemetry singletons: enablement, sampling, and the flight-
    # recorder dump dir (<store.root>/telemetry by default) all apply
    # here, so a live `onix serve` records spans and routes postmortem
    # dumps without any extra wiring.
    from onix.utils import telemetry
    telemetry.apply_config(cfg.telemetry)
    handler = type("BoundOAHandler", (OAHandler,), {"cfg": cfg})
    return OAServer((host, port), handler)


def run_serve(cfg: OnixConfig, port: int = DEFAULT_PORT,
              host: str = "127.0.0.1") -> int:
    server = make_server(cfg, port, host)
    print(f"onix serve: dashboards at http://{host}:{port}/ "
          f"(data from {cfg.oa.data_dir})")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
    return 0


def serve_background(cfg: OnixConfig, port: int = 0,
                     host: str = "127.0.0.1") -> tuple[ThreadingHTTPServer, int]:
    """Start the server on a daemon thread (tests, `onix demo`);
    port 0 picks a free port. Returns (server, bound_port)."""
    server = make_server(cfg, port, host)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server, server.server_address[1]
