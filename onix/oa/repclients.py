"""Network-backed reputation clients with failure discipline.

The reference's OA layer enriches suspicious connects through external
reputation services — McAfee GTI and Facebook ThreatExchange plugin
clients (SURVEY.md §2.1 #12; reference README.md:45-48 "attack
heuristics"). Those services need credentials and egress, so what this
module owns is the part that makes a network client PRODUCTION-grade
rather than a demo: request batching, per-request timeouts, bounded
retries with exponential backoff (5xx/transport errors only — a 4xx is
a contract bug and retrying it is abuse), a circuit breaker that stops
hammering a dead service, and a TTL cache so one run never asks twice.

Enrichment is advisory: every failure path degrades to "NONE" rather
than blocking the scoring pipeline (fail-open). The transport is
injectable, so the discipline is fully testable offline — and a real
deployment points the same client at its service endpoint.
"""

from __future__ import annotations

import json
import logging
import os
import time
import urllib.error
import urllib.request
from urllib.parse import quote

from onix.oa.components import REPUTATION_REGISTRY, ReputationClient

log = logging.getLogger("onix.oa.reputation")


class TransportError(RuntimeError):
    """Connection-level failure (DNS, refused, timeout) — retryable."""


def _urllib_transport(url: str, payload: bytes, timeout: float,
                      headers: dict) -> tuple[int, bytes]:
    req = urllib.request.Request(url, data=payload, method="POST",
                                 headers={"Content-Type": "application/json",
                                          **headers})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:          # non-2xx WITH a response
        return e.code, e.read()
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        raise TransportError(str(e)) from e


class CircuitBreaker:
    """Open after `threshold` consecutive failures; half-open (one trial
    request allowed) after `cooldown` seconds."""

    def __init__(self, threshold: int = 5, cooldown: float = 60.0):
        self.threshold = threshold
        self.cooldown = cooldown
        self.failures = 0
        self.opened_at: float | None = None

    def allow(self) -> bool:
        if self.opened_at is None:
            return True
        if time.monotonic() - self.opened_at >= self.cooldown:
            return True     # half-open: let one trial through
        return False

    def record(self, ok: bool) -> None:
        if ok:
            self.failures = 0
            self.opened_at = None
        else:
            self.failures += 1
            if self.failures >= self.threshold:
                self.opened_at = time.monotonic()


class HTTPReputationClient(ReputationClient):
    """Batched JSON-over-HTTP reputation lookups with failure discipline.

    Wire contract (the shape GTI/ThreatExchange-style services share):
    POST {"indicators": [...]} -> {"results": {indicator: LEVEL}} with
    LEVEL in NONE/LOW/MEDIUM/HIGH; unknown indicators may be omitted.
    Subclass and override `encode_request`/`parse_response` to adapt a
    specific vendor's schema — the discipline underneath is shared.
    """

    name = "http"

    def __init__(self, url: str = "", *, api_key: str = "",
                 batch_size: int = 100, timeout: float = 5.0,
                 max_retries: int = 3, backoff_base: float = 0.25,
                 cache_ttl: float = 3600.0, transport=None,
                 breaker: CircuitBreaker | None = None, sleep=time.sleep):
        if not url:
            raise ValueError("http reputation plugin needs a URL "
                             "(spec: http:<url>)")
        self.url = url
        self.api_key = api_key
        self.batch_size = batch_size
        self.timeout = timeout
        self.max_retries = max_retries
        self.backoff_base = backoff_base
        self.cache_ttl = cache_ttl
        self.transport = transport or _urllib_transport
        self.breaker = breaker or CircuitBreaker()
        self.sleep = sleep
        self._cache: dict[str, tuple[float, str]] = {}
        self.stats = {"requests": 0, "retries": 0, "failures": 0,
                      "cache_hits": 0, "breaker_skips": 0}

    # -- vendor adaptation points -----------------------------------------

    def encode_request(self, batch: list[str]) -> bytes:
        return json.dumps({"indicators": batch}).encode()

    def parse_response(self, body: bytes) -> dict[str, str]:
        data = json.loads(body)
        results = data.get("results", {})
        if not isinstance(results, dict):
            raise ValueError("results must be an object")
        return {str(k): str(v).upper() for k, v in results.items()}

    # -- discipline --------------------------------------------------------

    def _post_batch(self, batch: list[str]) -> dict[str, str]:
        """One batch with retries; raises on definitive failure."""
        headers = {"Authorization": f"Bearer {self.api_key}"} \
            if self.api_key else {}
        last: Exception | None = None
        for attempt in range(self.max_retries + 1):
            if attempt:
                self.stats["retries"] += 1
                # Exponential backoff; deterministic (tests inject sleep).
                self.sleep(self.backoff_base * (2 ** (attempt - 1)))
            try:
                self.stats["requests"] += 1
                status, body = self.transport(self.url,
                                              self.encode_request(batch),
                                              self.timeout, headers)
            except TransportError as e:
                last = e
                continue
            if 200 <= status < 300:
                return self.parse_response(body)
            if 500 <= status < 600 or status == 429:
                last = RuntimeError(f"HTTP {status}")
                continue
            # 4xx: our request is wrong; retrying is abuse. Definitive.
            raise RuntimeError(f"HTTP {status} (not retryable)")
        raise last if last else RuntimeError("unreachable")

    def check(self, values: list[str]) -> dict[str, str]:
        now = time.monotonic()
        out: dict[str, str] = {}
        todo: list[str] = []
        for v in values:
            hit = self._cache.get(v)
            if hit is not None and now - hit[0] < self.cache_ttl:
                out[v] = hit[1]
                self.stats["cache_hits"] += 1
            else:
                todo.append(v)
        for lo in range(0, len(todo), self.batch_size):
            batch = todo[lo:lo + self.batch_size]
            if not self.breaker.allow():
                self.stats["breaker_skips"] += 1
                out.update({v: "NONE" for v in batch})   # fail-open
                continue
            try:
                got = self._post_batch(batch)
                self.breaker.record(True)
            except Exception as e:
                self.breaker.record(False)
                self.stats["failures"] += 1
                log.warning("reputation lookup failed (%s): %s — "
                            "degrading %d indicators to NONE",
                            self.url, e, len(batch))
                out.update({v: "NONE" for v in batch})   # fail-open
                continue
            for v in batch:
                level = got.get(v, "NONE")
                if level not in ("NONE", "LOW", "MEDIUM", "HIGH"):
                    level = "NONE"
                self._cache[v] = (now, level)
                out[v] = level
        return out


REPUTATION_REGISTRY["http"] = HTTPReputationClient


class GTIReputationClient(HTTPReputationClient):
    """McAfee GTI-style adapter (SURVEY.md §2.1 #12; the reference's
    `oni-gti` plugin). Wire shape: POST {"queries": [{"url": <v>}]} ->
    {"answers": [{"url": <v>, "rep": <int>}]} — the TrustedSource-style
    numeric reputation, higher = riskier. The adapter owns only the
    schema and the rep -> NONE/LOW/MEDIUM/HIGH mapping (thresholds
    configurable); batching, retries, backoff, breaker, cache and
    fail-open all come from HTTPReputationClient. Spec: `gti:<url>`
    (+ ONIX_GTI_API_KEY for auth)."""

    name = "gti"

    def __init__(self, url: str = "", *, low: int = 30, medium: int = 50,
                 high: int = 70, **kw):
        kw.setdefault("api_key", os.environ.get("ONIX_GTI_API_KEY", ""))
        super().__init__(url, **kw)
        if not low <= medium <= high:
            raise ValueError("thresholds must be ordered low<=medium<=high")
        self.thresholds = (low, medium, high)
        _require_key_for_network(self, "ONIX_GTI_API_KEY")

    def encode_request(self, batch: list[str]) -> bytes:
        return json.dumps({"queries": [{"url": v} for v in batch]}).encode()

    def parse_response(self, body: bytes) -> dict[str, str]:
        data = json.loads(body)
        answers = data.get("answers", [])
        if not isinstance(answers, list):
            raise ValueError("answers must be a list")
        low, medium, high = self.thresholds
        out: dict[str, str] = {}
        for a in answers:
            # One malformed answer must not poison the batch: skip it
            # (its indicator degrades to NONE downstream) and keep the
            # valid verdicts. The isinstance gate matters: a non-dict
            # entry (e.g. a bare string) raises AttributeError on
            # .get — which is NOT in the caught set — and would
            # fail-open the WHOLE batch to NONE via the transport
            # handler instead of degrading one answer.
            if not isinstance(a, dict):
                continue
            try:
                rep = int(a.get("rep", 0))
                url = str(a["url"])
            except (TypeError, ValueError, KeyError):
                continue
            out[url] = ("HIGH" if rep >= high else
                        "MEDIUM" if rep >= medium else
                        "LOW" if rep >= low else "NONE")
        return out


class ThreatExchangeClient(HTTPReputationClient):
    """Facebook ThreatExchange-style adapter (the reference's `oni-tx`
    plugin). Wire shape: the Graph API batch envelope — POST
    {"batch": [{"method": "GET", "relative_url":
    "threat_descriptors?text=<v>&..."}]} with an access token; each
    sub-response body is {"data": [{"indicator": ..,
    "severity": INFO|WARNING|SUSPICIOUS|SEVERE|APOCALYPSE}]}. The
    worst severity over a value's descriptors maps to the level.
    Spec: `threatexchange:<url>` (+ ONIX_TX_ACCESS_TOKEN)."""

    name = "threatexchange"

    _SEVERITY = {"APOCALYPSE": "HIGH", "SEVERE": "HIGH",
                 "SUSPICIOUS": "MEDIUM", "WARNING": "LOW"}
    _RANK = {"NONE": 0, "LOW": 1, "MEDIUM": 2, "HIGH": 3}

    def __init__(self, url: str = "", **kw):
        kw.setdefault("api_key", os.environ.get("ONIX_TX_ACCESS_TOKEN", ""))
        # The Graph batch API rejects envelopes above 50 sub-requests.
        kw.setdefault("batch_size", 50)
        super().__init__(url, **kw)
        self._current_batch: list[str] | None = None
        _require_key_for_network(self, "ONIX_TX_ACCESS_TOKEN")

    def encode_request(self, batch: list[str]) -> bytes:
        return json.dumps({"batch": [
            {"method": "GET",
             "relative_url": ("threat_descriptors?text="
                              f"{quote(v)}&fields=indicator,severity")}
            for v in batch]}).encode()

    def _post_batch(self, batch: list[str]) -> dict[str, str]:
        # Stash the request order: the Graph batch API guarantees
        # response order matches request order, and the text= search
        # returns descriptors whose `indicator` strings are routinely
        # NOT byte-identical to the query (URL forms, subdomains) —
        # keying by indicator would silently drop and NONE-cache real
        # hits. parse_response attributes the i-th sub-response to the
        # i-th queried value instead.
        self._current_batch = list(batch)
        try:
            return super()._post_batch(batch)
        finally:
            self._current_batch = None

    def parse_response(self, body: bytes) -> dict[str, str]:
        responses = json.loads(body)
        if not isinstance(responses, list):
            raise ValueError("batch response must be a list")
        queried = getattr(self, "_current_batch", None) or []
        out: dict[str, str] = {}
        for i, sub in enumerate(responses):
            if i >= len(queried):
                break
            value = queried[i]
            # Graph batch: each entry is {"code": .., "body": "<json>"}
            # (body is a STRING per the batch API contract). Malformed
            # entries skip THIS value only.
            try:
                if not isinstance(sub, dict) or int(sub.get("code")) != 200:
                    continue
                payload = sub.get("body", "{}")
                data = json.loads(payload) if isinstance(payload, str) \
                    else payload
                descriptors = data.get("data", [])
            except (TypeError, ValueError):
                continue
            worst = "NONE"
            for d in descriptors:
                lvl = self._SEVERITY.get(
                    str(d.get("severity", "")).upper(), "NONE")
                if self._RANK[lvl] > self._RANK[worst]:
                    worst = lvl
            out[value] = worst
        return out


def _require_key_for_network(client: HTTPReputationClient,
                             env_var: str) -> None:
    """Fail FAST on the one misconfiguration detectable at construction:
    a vendor client on the real network transport with no credential
    would 401 on every lookup and silently enrich nothing (4xx is
    non-retryable, check() fail-opens to NONE). Injected transports
    (tests, offline demos) stay keyless by design."""
    if not client.api_key and client.transport is _urllib_transport:
        raise ValueError(
            f"{client.name} reputation client has no API key; set "
            f"{env_var} (or inject a transport for offline use)")


REPUTATION_REGISTRY["gti"] = GTIReputationClient
REPUTATION_REGISTRY["threatexchange"] = ThreatExchangeClient
