"""Persistent analyst kernels for the hosted notebooks.

The reference's dashboards are served BY a live IPython notebook server
(reference README.md:55) — the analyst edits cells and re-runs them
against a kernel that keeps state between executions. onix's r03 server
could only run a whole notebook in a fresh kernel per request; this
module supplies the missing interactive half (VERDICT r03 missing #3):

* `KernelSession` — one persistent Python worker SUBPROCESS per
  session. Cells execute in the worker's single namespace (state
  carries across calls exactly like an IPython kernel); the worker is
  isolated so an analyst cell that crashes, leaks, or loops can never
  take down the dashboard server — a hung cell is killed at its
  deadline and reported as an error while the server keeps serving.
* IPython-style display: stdout/stderr are captured per cell, and when
  the cell's last statement is an expression its value is rendered —
  `_repr_html_` (pandas frames render as tables in the dashboard) or
  `repr`.
* `KernelManager` — the server's session registry keyed by analyst
  session id, with an idle-eviction sweep so abandoned dashboards
  don't accumulate interpreters.

The wire format between server and worker is one JSON object per line
over the worker's stdin/stdout; the worker writes cell prints to a
redirected buffer, so the protocol channel can never be corrupted by
analyst output.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import threading
import time
import uuid

_WORKER_SOURCE = r'''
import contextlib, io, json, sys, traceback

def _render(value):
    if value is None:
        return None, None
    html = None
    rh = getattr(type(value), "_repr_html_", None)
    if rh is not None:
        try:
            html = rh(value)
        except Exception:
            html = None
    try:
        text = repr(value)
    except Exception as e:
        text = f"<unreprable {type(value).__name__}: {e}>"
    return text, html

def main():
    import ast
    ns = {"__name__": "__main__"}
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        req = json.loads(line)
        code = req.get("code", "")
        out, err = io.StringIO(), io.StringIO()
        resp = {"id": req.get("id")}
        try:
            tree = ast.parse(code, mode="exec")
            # IPython semantics: a trailing expression is the cell's
            # displayed value.
            tail = None
            if tree.body and isinstance(tree.body[-1], ast.Expr):
                tail = ast.Expression(tree.body[-1].value)
                tree.body = tree.body[:-1]
            with contextlib.redirect_stdout(out), \
                    contextlib.redirect_stderr(err):
                exec(compile(tree, "<cell>", "exec"), ns)
                value = (eval(compile(tail, "<cell>", "eval"), ns)
                         if tail is not None else None)
            text, html = _render(value)
            resp.update(ok=True, result=text, result_html=html)
        except BaseException:
            resp.update(ok=False, error=traceback.format_exc())
        resp["stdout"] = out.getvalue()[-100_000:]
        resp["stderr"] = err.getvalue()[-100_000:]
        sys.stdout.write(json.dumps(resp) + "\n")
        sys.stdout.flush()

main()
'''


class KernelDead(RuntimeError):
    pass


class KernelSession:
    """One persistent worker interpreter (≙ an IPython kernel)."""

    def __init__(self, env: dict | None = None,
                 cleanup_files: list[str] | None = None):
        self.id = uuid.uuid4().hex[:16]
        self.last_used = time.time()
        self._cleanup_files = list(cleanup_files or [])
        worker_env = dict(os.environ)
        repo_root = str(pathlib.Path(__file__).resolve().parents[2])
        worker_env["PYTHONPATH"] = os.pathsep.join(
            [repo_root, worker_env.get("PYTHONPATH", "")]).rstrip(os.pathsep)
        if env:
            worker_env.update(env)
        self._proc = subprocess.Popen(
            [sys.executable, "-c", _WORKER_SOURCE],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, env=worker_env)
        self._lock = threading.Lock()   # one cell at a time per kernel
        self._seq = 0

    @property
    def alive(self) -> bool:
        return self._proc.poll() is None

    def execute(self, code: str, timeout: float = 120.0) -> dict:
        """Run one cell in the persistent namespace. On timeout or a
        dead worker the kernel is killed and KernelDead raises — the
        caller restarts the session (state is gone either way)."""
        with self._lock:
            if not self.alive:
                raise KernelDead("kernel process exited")
            self.last_used = time.time()
            self._seq += 1
            req = {"id": self._seq, "code": code}
            try:
                self._proc.stdin.write(json.dumps(req) + "\n")
                self._proc.stdin.flush()
            except (BrokenPipeError, OSError) as e:
                self.close()
                raise KernelDead(f"kernel stdin closed: {e}") from e
            # Read with a deadline on a side thread: readline has no
            # timeout, and a looping cell must not wedge the server.
            box: list = []

            def read():
                box.append(self._proc.stdout.readline())

            t = threading.Thread(target=read, daemon=True)
            t.start()
            t.join(timeout)
            timed_out = t.is_alive()        # before close() unblocks it
            if timed_out or not box or not box[0]:
                self.close()
                raise KernelDead(
                    f"cell exceeded {timeout:.0f}s (kernel killed; "
                    "restart the session)" if timed_out
                    else "kernel process exited mid-cell")
            resp = json.loads(box[0])
            self.last_used = time.time()
            return resp

    def close(self) -> None:
        if self._proc.poll() is None:
            self._proc.kill()
        try:
            self._proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass
        for p in self._cleanup_files:
            try:
                os.unlink(p)
            except OSError:
                pass
        self._cleanup_files = []


class KernelManager:
    """Session registry for the dashboard server."""

    #: Lock discipline, machine-checked by the `locks` analysis pass.
    GUARDED_BY = {"_sessions": "_lock"}

    def __init__(self, idle_timeout_s: float = 3600.0, max_sessions: int = 8):
        self._sessions: dict[str, KernelSession] = {}
        self._lock = threading.Lock()
        self.idle_timeout_s = idle_timeout_s
        self.max_sessions = max_sessions

    def start(self, env: dict | None = None,
              cleanup_files: list[str] | None = None) -> KernelSession:
        with self._lock:
            self._evict_locked()
            if len(self._sessions) >= self.max_sessions:
                # Drop the longest-idle session rather than refusing the
                # analyst in front of the dashboard.
                oldest = min(self._sessions.values(),
                             key=lambda s: s.last_used)
                oldest.close()
                del self._sessions[oldest.id]
            s = KernelSession(env=env, cleanup_files=cleanup_files)
            self._sessions[s.id] = s
            return s

    def get(self, session_id: str) -> KernelSession | None:
        with self._lock:
            self._evict_locked()
            return self._sessions.get(session_id)

    def stop(self, session_id: str) -> bool:
        with self._lock:
            s = self._sessions.pop(session_id, None)
        if s is None:
            return False
        s.close()
        return True

    def drop(self, session_id: str) -> None:
        """Forget a session known dead (execute raised KernelDead)."""
        with self._lock:
            s = self._sessions.pop(session_id, None)
        if s is not None:
            s.close()

    def close_all(self) -> None:
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for s in sessions:
            s.close()

    # lint: holds[_lock] -- the _locked suffix is the contract: every caller holds self._lock
    def _evict_locked(self) -> None:
        cutoff = time.time() - self.idle_timeout_s
        for sid in [sid for sid, s in self._sessions.items()
                    if s.last_used < cutoff or not s.alive]:
            self._sessions[sid].close()
            del self._sessions[sid]
