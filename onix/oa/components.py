"""OA enrichment components: GeoIP, domain context, reputation plugins.

The reference ships these as `oa/components/{geoloc,reputation,...}`
(SURVEY.md §2.1 #12 [R-med]) with network-backed reputation clients
(McAfee GTI, Facebook ThreatExchange). onix keeps the same pluggable
shape but every component works offline: GeoIP from a local CIDR CSV
database, reputation from local indicator lists, with a registry so
network-backed clients can be added without touching the engine.
"""

from __future__ import annotations

import dataclasses
import pathlib

import numpy as np
import pandas as pd

from onix.utils.features import entropy_array, subdomain_split

# ---------------------------------------------------------------------------
# IP handling
# ---------------------------------------------------------------------------


def ip_to_u32(ips) -> np.ndarray:
    """Dotted-quad strings -> uint32 (invalid/malformed -> 0)."""
    out = np.zeros(len(ips), np.uint32)
    for i, s in enumerate(ips):
        parts = str(s).split(".")
        if len(parts) != 4:
            continue
        try:
            a, b, c, d = (int(p) for p in parts)
        except ValueError:
            continue
        if max(a, b, c, d) > 255 or min(a, b, c, d) < 0:
            continue
        out[i] = (a << 24) | (b << 16) | (c << 8) | d
    return out


def cidr_to_range(cidr: str) -> tuple[int, int]:
    """'10.0.0.0/8' -> (start, end) inclusive uint32 bounds.

    Raises on a malformed network part: ip_to_u32's lenient invalid→0
    mapping is right for event enrichment, but a bad *database* row would
    silently claim address space based at 0.0.0.0 and mislabel unrelated
    IPs — fail loudly at load time instead.
    """
    net, _, bits = cidr.partition("/")
    prefix = int(bits) if bits else 32
    if not 0 <= prefix <= 32:
        raise ValueError(f"bad prefix in {cidr!r}")
    parts = net.split(".")
    if (len(parts) != 4
            or not all(p.isdigit() and int(p) <= 255 for p in parts)):
        raise ValueError(f"bad network address in {cidr!r}")
    base = int(ip_to_u32([net])[0])
    span = 1 << (32 - prefix)
    start = base & ~(span - 1) & 0xFFFFFFFF
    return start, start + span - 1


# ---------------------------------------------------------------------------
# GeoIP — offline CIDR database
# ---------------------------------------------------------------------------

_GEO_COLS = ("geo_country", "geo_city", "geo_lat", "geo_lon", "geo_isp")

# Reserved/special-use ranges (RFC 1918/5735) — the always-available
# fallback database, so internal hosts are labeled even with no db file.
_BUILTIN_RANGES = [
    ("10.0.0.0/8", "internal", "rfc1918", 0.0, 0.0, "internal"),
    ("172.16.0.0/12", "internal", "rfc1918", 0.0, 0.0, "internal"),
    ("192.168.0.0/16", "internal", "rfc1918", 0.0, 0.0, "internal"),
    ("127.0.0.0/8", "loopback", "loopback", 0.0, 0.0, "loopback"),
    ("169.254.0.0/16", "linklocal", "linklocal", 0.0, 0.0, "linklocal"),
    ("224.0.0.0/4", "multicast", "multicast", 0.0, 0.0, "multicast"),
    # RFC 5737 documentation nets at fictional-but-plausible demo
    # coordinates: synthetic telemetry (onix.pipelines.synth) draws its
    # external anomaly peers here, so the demo dashboards' geo view is
    # populated without a real GeoIP database. A user-supplied DB row
    # for the same prefix overrides these (later-listed wins ties).
    ("192.0.2.0/24", "demo-apac", "testnet-1", -33.87, 151.21, "demo"),
    ("198.51.100.0/24", "demo-emea", "testnet-2", 48.86, 2.35, "demo"),
    ("203.0.113.0/24", "demo-amer", "testnet-3", 37.77, -122.42, "demo"),
]


@dataclasses.dataclass
class GeoIPDB:
    """Sorted non-overlapping CIDR ranges with location/ISP metadata.

    Lookup is a vectorized searchsorted over range starts (O(log n) per
    IP) — the offline stand-in for the reference's GeoIP component.
    """

    starts: np.ndarray          # uint32 [n] ascending
    ends: np.ndarray            # uint32 [n] inclusive
    meta: pd.DataFrame          # [n] columns _GEO_COLS

    @classmethod
    def from_rows(cls, rows) -> "GeoIPDB":
        """rows: iterable of (cidr, country, city, lat, lon, isp).

        Ranges may nest/overlap (a user CSV layered over the builtin
        reserved ranges); they are flattened to disjoint segments with
        the most-specific (longest-prefix, latest-listed on ties) range
        owning each segment, so lookup stays a single searchsorted.
        """
        parsed = []
        for cidr, country, city, lat, lon, isp in rows:
            start, end = cidr_to_range(str(cidr))
            parsed.append((start, end, (str(country), str(city),
                           float(lat), float(lon), str(isp))))
        # Sweep over boundaries; a stack of covering ranges makes the
        # innermost range own each elementary segment.
        events = []     # (ip, kind, idx): kind 0 = open, 1 = close-after
        for i, (s, e, _) in enumerate(parsed):
            events.append((s, 0, i))
            events.append((e + 1, 1, i))
        # At the same boundary, closes apply before opens; later-listed
        # (more specific, since builtins are prepended) ranges win ties.
        events.sort(key=lambda t: (t[0], t[1] == 0))
        seg_starts, seg_ends, seg_meta = [], [], []
        stack: list[int] = []

        def owner() -> int:
            # innermost = smallest span; tie -> latest listed
            return min(stack, key=lambda i: (parsed[i][1] - parsed[i][0],
                                             -i))

        prev = None
        for ip, kind, idx in events:
            if stack and prev is not None and ip > prev:
                seg_starts.append(prev)
                seg_ends.append(ip - 1)
                seg_meta.append(parsed[owner()][2])
            if kind == 0:
                stack.append(idx)
            else:
                stack.remove(idx)
            prev = ip
        n = len(seg_starts)
        return cls(
            starts=np.asarray(seg_starts, np.uint32).reshape(n),
            ends=np.asarray(seg_ends, np.uint32).reshape(n),
            meta=pd.DataFrame(seg_meta, columns=list(_GEO_COLS)))

    @classmethod
    def builtin(cls) -> "GeoIPDB":
        return cls.from_rows(_BUILTIN_RANGES)

    @classmethod
    def load(cls, path: str | pathlib.Path) -> "GeoIPDB":
        """CSV with columns network,country,city,latitude,longitude,isp;
        builtin reserved ranges are merged in underneath."""
        db = pd.read_csv(path, dtype=str).fillna("")
        rows = [(r["network"], r.get("country", ""), r.get("city", ""),
                 float(r.get("latitude") or 0.0),
                 float(r.get("longitude") or 0.0), r.get("isp", ""))
                for _, r in db.iterrows()]
        return cls.from_rows(list(_BUILTIN_RANGES) + rows)

    def lookup(self, ips) -> pd.DataFrame:
        """Enrichment frame (columns _GEO_COLS) aligned with `ips`;
        unmatched IPs get country 'unknown'."""
        vals = ip_to_u32(list(ips))
        if len(self.starts) == 0:
            out = pd.DataFrame(index=range(len(vals)),
                               columns=list(_GEO_COLS))
            out[["geo_country", "geo_city", "geo_isp"]] = "unknown"
            out[["geo_lat", "geo_lon"]] = 0.0
            return out
        idx = np.searchsorted(self.starts, vals, side="right") - 1
        idx_c = np.clip(idx, 0, len(self.starts) - 1)
        hit = (idx >= 0) & (vals <= self.ends[idx_c])
        out = self.meta.iloc[idx_c].reset_index(drop=True)
        out.loc[~hit, ["geo_country", "geo_city", "geo_isp"]] = "unknown"
        out.loc[~hit, ["geo_lat", "geo_lon"]] = 0.0
        return out


# ---------------------------------------------------------------------------
# Domain context
# ---------------------------------------------------------------------------


def domain_context(names, top_domains: list[str] | None = None) -> pd.DataFrame:
    """Registered-domain decomposition + entropy + popularity rank.

    `top_domains` is an ordered popular-domains list (Alexa-style, the
    reference's domain/ISP mapping input [R-med]); rank is 1-based
    position or -1 when absent/unknown.
    """
    ranks = {d: i + 1 for i, d in enumerate(top_domains or [])}
    subs, slds, dots, valid = [], [], [], []
    for name in names:
        sub, sld, n, ok = subdomain_split(str(name))
        subs.append(sub)
        slds.append(sld)
        dots.append(n)
        valid.append(ok)
    ent = entropy_array(np.asarray([str(n) for n in names], object))
    return pd.DataFrame({
        "domain": np.asarray(slds, object),
        "subdomain": np.asarray(subs, object),
        "n_labels": np.asarray(dots, np.int32),
        "tld_valid": np.asarray(valid, bool),
        "name_entropy": np.round(ent, 3),
        "domain_rank": np.asarray(
            [ranks.get(d, -1) for d in slds], np.int32),
    })


# ---------------------------------------------------------------------------
# Reputation plugins
# ---------------------------------------------------------------------------


class ReputationClient:
    """Base reputation service client.

    The reference's clients call external services (GTI, ThreatExchange
    — SURVEY.md §2.1 #12); subclasses implement `check` over a batch of
    indicators (IPs or domains) and return indicator -> level, one of
    NONE/LOW/MEDIUM/HIGH.
    """

    name = "base"

    def check(self, values: list[str]) -> dict[str, str]:
        raise NotImplementedError


class NoopReputation(ReputationClient):
    name = "noop"

    def check(self, values: list[str]) -> dict[str, str]:
        return {v: "NONE" for v in values}


class LocalListReputation(ReputationClient):
    """Offline indicator list: newline-separated `indicator[,level]`
    entries; bare indicators default to HIGH. The air-gapped stand-in
    for the reference's network reputation services.

    Domain indicators match by suffix (an `evil.biz` entry flags
    `beacon.x0.evil.biz`); IPs match exactly.
    """

    name = "local"

    def __init__(self, path: str | pathlib.Path):
        self.levels: dict[str, str] = {}
        for line in pathlib.Path(path).read_text().splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            ind, _, level = line.partition(",")
            self.levels[ind.strip().lower()] = (level.strip().upper()
                                                or "HIGH")

    def _lookup(self, value: str) -> str:
        v = value.lower().rstrip(".")
        hit = self.levels.get(v)
        if hit is not None:
            return hit
        if not v or v[0].isdigit():     # IP-like: exact match only
            return "NONE"
        labels = v.split(".")
        for i in range(1, len(labels) - 1):     # parent suffixes, not bare TLD
            hit = self.levels.get(".".join(labels[i:]))
            if hit is not None:
                return hit
        return "NONE"

    def check(self, values: list[str]) -> dict[str, str]:
        return {v: self._lookup(str(v)) for v in values}


REPUTATION_REGISTRY = {
    "noop": NoopReputation,
    "local": LocalListReputation,
}


def build_reputation(specs: str) -> list[ReputationClient]:
    """Parse comma-separated plugin specs: `local:<path>` / `noop` /
    `http:<url>` (spec splits at the FIRST colon, so URLs pass through
    intact)."""
    from onix.oa import repclients  # noqa: F401  (registers "http")
    clients: list[ReputationClient] = []
    for spec in (s.strip() for s in specs.split(",") if s.strip()):
        name, _, arg = spec.partition(":")
        if name not in REPUTATION_REGISTRY:
            raise ValueError(
                f"unknown reputation plugin {name!r}; "
                f"have {sorted(REPUTATION_REGISTRY)}")
        cls = REPUTATION_REGISTRY[name]
        clients.append(cls(arg) if arg else cls())
    return clients


_LEVELS = ("NONE", "LOW", "MEDIUM", "HIGH")


def reputation_column(clients: list[ReputationClient], values) -> np.ndarray:
    """Max level across clients per value ('NONE' when no clients)."""
    vals = [str(v) for v in values]
    best = np.zeros(len(vals), np.int32)
    for client in clients:
        got = client.check(sorted(set(vals)))
        lvl = np.asarray([_LEVELS.index(got.get(v, "NONE")) for v in vals],
                         np.int32)
        best = np.maximum(best, lvl)
    return np.asarray([_LEVELS[i] for i in best], object)
