"""Operational Analytics — the oni-oa batch engine equivalent.

The reference's L5 (SURVEY.md §2.1 #12): per day/type, pull the ML
results CSV, enrich (GeoIP, domain/ISP mapping, reputation plugins),
and emit the per-date JSON/CSV files the analyst UI reads
(reference README.md:45-48; `.gitmodules:10-12`).
"""
