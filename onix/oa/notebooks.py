"""Analyst scoring-notebook templates — the reference's `ipynb/` dir.

The reference closes its feedback loop through per-datatype IPython
notebooks served next to the dashboards (SURVEY.md §2.1 #14: "In-
dashboard notebooks (edge/threat investigation) where the analyst labels
results"; reference README.md:48,55). onix ships the same artifact:
generated `.ipynb` templates that load the day's enriched results,
summarize the top suspects, and write labels through
`onix.oa.feedback.append_feedback` — the identical CSV contract the
dashboard's Save button and `onix label` use, so all three label paths
converge on one noise-filter input.

`onix setup` installs the templates under `<oa.data_dir>/notebooks/`,
which `onix serve` exposes at `/data/notebooks/` for download into any
Jupyter instance.
"""

from __future__ import annotations

import json
import pathlib

DATATYPES = ("flow", "dns", "proxy")

_CELLS = [
    ("markdown", """# onix — {datatype} threat investigation

Score a day of surfaced **{datatype}** suspicious connects and feed your
labels back to the model. Labels: `1` high threat, `2` medium, `3`
benign — only *benign* labels bias the next run (duplicating a
confirmed threat would teach the model to stop surfacing it)."""),
    ("code", """import os
import pandas as pd

from onix.config import load_config
from onix.oa.engine import oa_dir
from onix.oa.feedback import append_feedback

DATATYPE = "{datatype}"
DATE = os.environ.get("ONIX_DATE", "2016-07-08")
cfg = load_config(os.environ.get("ONIX_CONFIG") or None)

day = oa_dir(cfg, DATATYPE, DATE)
results = pd.read_csv(day / "suspicious.csv")
print(f"{{len(results)}} suspicious {datatype} events for {{DATE}}")"""),
    ("code", """# The most suspicious events, with enrichment columns.
results.head(20)"""),
    ("code", """# Label by dashboard rank, then run this cell to save.
# Example: ranks 3 and 7 are benign, rank 1 is a confirmed threat.
labels = {{
    # rank: label,
    # 3: 3,
    # 7: 3,
    # 1: 1,
}}

if labels:
    rows = results[results["rank"].isin(labels)].copy()
    rows["label"] = rows["rank"].map(labels)
    path = append_feedback(cfg, DATATYPE, DATE,
                           rows[["ip", "word", "rank", "score", "label"]])
    print(f"wrote {{len(rows)}} labels -> {{path}}")
else:
    print("no labels staged")"""),
]


def _notebook(datatype: str) -> dict:
    cells = []
    for i, (kind, src) in enumerate(_CELLS):
        text = src.format(datatype=datatype)
        cells.append({
            "cell_type": kind,
            "id": f"onix-{datatype}-{i}",   # required from nbformat 4.5
            "metadata": {},
            "source": text.splitlines(keepends=True),
            **({"outputs": [], "execution_count": None}
               if kind == "code" else {}),
        })
    return {
        "nbformat": 4,
        "nbformat_minor": 5,
        "metadata": {
            "kernelspec": {"name": "python3", "display_name": "Python 3",
                           "language": "python"},
            "language_info": {"name": "python"},
        },
        "cells": cells,
    }


def write_notebooks(dest_dir: str | pathlib.Path) -> list[pathlib.Path]:
    """Materialize the per-datatype templates; returns written paths."""
    dest = pathlib.Path(dest_dir)
    dest.mkdir(parents=True, exist_ok=True)
    out = []
    for t in DATATYPES:
        path = dest / f"{t}_threat_investigation.ipynb"
        path.write_text(json.dumps(_notebook(t), indent=1))
        out.append(path)
    return out


def code_cells(path: str | pathlib.Path) -> list[str]:
    """The notebook's code-cell sources (for tests and headless use)."""
    nb = json.loads(pathlib.Path(path).read_text())
    return ["".join(c["source"]) for c in nb["cells"]
            if c["cell_type"] == "code"]


# -- hosted notebooks (VERDICT r2 missing #4) ------------------------------
#
# The reference HOSTS live notebooks next to the dashboards (its UI is
# served from an IPython file server). onix goes one step further than
# file serving: `onix serve` renders any installed template as HTML at
# /notebooks/<datatype>.html and EXECUTES it against the current OA
# data dir on POST /notebooks/run — the analyst sees live outputs in
# the dashboard without a separate Jupyter deployment (the .ipynb
# download for a full Jupyter session still works).

def render_html(path: str | pathlib.Path, executed_nb=None) -> str:
    """Standalone HTML for a notebook: the template as-is, or an
    in-memory executed NotebookNode when `executed_nb` is given."""
    import nbformat
    from nbconvert import HTMLExporter

    nb = (executed_nb if executed_nb is not None
          else nbformat.read(str(path), as_version=4))
    body, _resources = HTMLExporter().from_notebook_node(nb)
    return body


def execute_to_html(path: str | pathlib.Path, *, date: str,
                    config_path: str | None = None,
                    timeout: int = 180) -> str:
    """Run the notebook headless (fresh python3 kernel) against the
    current config/date and render the result, tracebacks included
    (`allow_errors` — an analyst must SEE a broken cell, not get a 500).

    The kernel is a new interpreter: it inherits this process's env but
    not its sys.path or config object, so a parameter cell is injected
    that pins both (same contract the template reads via ONIX_DATE /
    ONIX_CONFIG)."""
    import nbformat
    from nbclient import NotebookClient

    nb = nbformat.read(str(path), as_version=4)
    repo_root = str(pathlib.Path(__file__).resolve().parents[2])
    lines = [
        "import os, sys",
        f"sys.path.insert(0, {repo_root!r})",
        f"os.environ['ONIX_DATE'] = {date!r}",
    ]
    if config_path:
        lines.append(f"os.environ['ONIX_CONFIG'] = {str(config_path)!r}")
    nb.cells.insert(0, nbformat.v4.new_code_cell(
        "\n".join(lines), metadata={"tags": ["injected-parameters"]}))
    NotebookClient(nb, timeout=timeout, kernel_name="python3",
                   allow_errors=True).execute()
    return render_html(path, executed_nb=nb)
