"""`onix setup` + `onix demo` — the oni-setup / demo-packaging equivalents.

The reference's oni-setup scripts create the HDFS dirs and Hive db/tables
and distribute the central config (SURVEY.md §2.1 #3, §3.4); its demo is
a Docker image with a precomputed 2016-07-08 dataset that doubles as the
integration-test fixture (SURVEY.md §2.1 #15, reference README.md:50-62).

onix setup: materialize the store layout (partitioned Parquet dirs in
place of Hive DDL) and archive the resolved config — idempotent.

onix demo: synthesize the demo day for all three datatypes, load the
store, run the full scoring pipeline and OA, and optionally serve the
dashboards — the one-command end-to-end slice.
"""

from __future__ import annotations

import pathlib

from onix.config import DATATYPES, OnixConfig

DEMO_DATE = "2016-07-08"        # the reference demo's canned date


def run_setup(cfg: OnixConfig) -> int:
    """Create the storage substrate; safe to re-run."""
    root = pathlib.Path(cfg.store.root)
    created = []
    for d in [root / t for t in DATATYPES] + [
            pathlib.Path(cfg.store.results_dir),
            pathlib.Path(cfg.store.feedback_dir),
            pathlib.Path(cfg.store.checkpoint_dir),
            pathlib.Path(cfg.oa.data_dir)]:
        if not d.exists():
            created.append(str(d))
        d.mkdir(parents=True, exist_ok=True)
    cfg.archive(root / "onix.config.json")
    # Analyst notebook templates (SURVEY.md §2.1 #14) next to the OA
    # data so `onix serve` exposes them at /data/notebooks/.
    from onix.oa.notebooks import write_notebooks
    write_notebooks(pathlib.Path(cfg.oa.data_dir) / "notebooks")
    print(f"onix setup: store at {root} "
          f"({len(created)} dirs created, config archived, "
          f"notebooks installed)")
    return 0


def run_demo(cfg: OnixConfig, n_events: int = 20000, serve: bool = False,
             port: int = 8889) -> int:
    """End-to-end demo on synthetic telemetry for DEMO_DATE."""
    from onix.oa.engine import run_oa
    from onix.pipelines.run import run_scoring
    from onix.pipelines.synth import (synth_dns_day, synth_flow_day,
                                      synth_proxy_day)
    from onix.store import Store

    run_setup(cfg)
    store = Store(cfg.store.root)
    gens = {"flow": synth_flow_day, "dns": synth_dns_day,
            "proxy": synth_proxy_day}
    for datatype in DATATYPES:
        if not store.has(datatype, DEMO_DATE):
            table, _anomalies = gens[datatype](n_events=n_events,
                                               date=DEMO_DATE, seed=7)
            store.write(datatype, DEMO_DATE, table)
            print(f"onix demo: synthesized {len(table)} {datatype} events")
        cfg.pipeline.datatype = datatype
        cfg.pipeline.date = DEMO_DATE
        rc = run_scoring(cfg)
        if rc:
            return rc
        rc = run_oa(cfg, DEMO_DATE, datatype)
        if rc:
            return rc
    if serve:
        from onix.oa.serve import run_serve
        print(f"onix demo: open http://127.0.0.1:{port}/flow/"
              f"suspicious.html#date={DEMO_DATE}")
        return run_serve(cfg, port=port)
    return 0
