"""onix — "ONI on XLA": a TPU-native network-security analytics framework.

A from-scratch re-design of Open Network Insight (ONI; reference umbrella at
/root/reference, see README.md:30-48 for the four product pillars) for
JAX/XLA/Pallas on TPU device meshes:

- **ingest**  — parallel telemetry ingestion (netflow / DNS / proxy) into a
  partitioned Parquet store (replaces oni-ingest + Kafka + Hive, reference
  README.md:35-38).
- **pipelines** — vectorized word creation per datatype (replaces oni-ml's
  Spark word-creation jobs, reference README.md:41-43).
- **models**  — LDA topic-model engines: batched collapsed Gibbs and online
  variational Bayes, pure JAX (replaces the oni-lda-c C/MPI engine,
  reference README.md:84).
- **parallel** — doc-sharded multi-chip inference with topic-sufficient-
  statistics psum over ICI (replaces MPI_Reduce/Bcast in oni-lda-c).
- **oa**      — operational-analytics batch engine: enrichment + per-date
  results for analyst dashboards (replaces oni-oa, reference README.md:45-48).

Unlike the reference — a constellation of Scala/Spark, C/MPI, Python 2 and
Bash glued together by files and ssh — onix is one package with one config
system, one storage substrate, and one compiled compute path.
"""

__version__ = "0.1.0"

from onix.config import OnixConfig, LDAConfig, load_config  # noqa: F401
