"""onix command-line interface.

Mirrors the reference's operator surface (SURVEY.md §3.1, §7.1.8):
`ml_ops.sh <YYYYMMDD> <flow|dns|proxy> [TOL] [MAXRESULTS]` becomes
`onix score <date> <type> [--tol] [--max-results]`, plus `ingest` and
`oa` subcommands for the other two pillars (reference README.md:35-48).

Subcommands are registered lazily so `onix config` works before the
heavier pipeline modules import JAX.
"""

from __future__ import annotations

import argparse
import sys

from onix.config import load_config


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", "-c", default=None,
                   help="YAML/JSON config file")
    p.add_argument("--set", "-s", action="append", default=[],
                   metavar="KEY.PATH=VALUE", dest="overrides",
                   help="config override (repeatable)")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="onix",
        description="TPU-native network-security analytics (ONI on XLA)")
    sub = parser.add_subparsers(dest="command", required=True)

    p_cfg = sub.add_parser("config", help="print the resolved configuration")
    _add_common(p_cfg)

    p_score = sub.add_parser(
        "score", help="run the suspicious-connects scoring pipeline for one "
                      "day of one datatype (the ml_ops.sh equivalent)")
    _add_common(p_score)
    p_score.add_argument("date", help="day to score, YYYY-MM-DD")
    p_score.add_argument("datatype", choices=("flow", "dns", "proxy"))
    p_score.add_argument("--tol", type=float, default=None)
    p_score.add_argument("--max-results", type=int, default=None)
    p_score.add_argument("--engine", choices=("gibbs", "svi", "sharded"),
                         default="gibbs",
                         help="gibbs: single-device batched collapsed "
                              "Gibbs; svi: online VB; sharded: multi-"
                              "chip doc/vocab-sharded Gibbs over the "
                              "mesh.dp x mesh.mp mesh")
    p_score.add_argument("--fault-inject", type=int, default=None,
                         metavar="SWEEP",
                         help="testing hook: simulate a preemption after "
                              "this sweep (re-run resumes from checkpoint)")
    p_score.add_argument("--fault-plan", default=None, metavar="PLAN",
                         help="chaos drill: declarative fault plan, e.g. "
                              "'fit:sweep@8=preempt,ckpt:save@1=torn' "
                              "(docs/ROBUSTNESS.md; also env "
                              "ONIX_FAULT_PLAN)")

    p_ingest = sub.add_parser(
        "ingest", help="decode and load raw telemetry into the store")
    _add_common(p_ingest)
    p_ingest.add_argument("datatype", choices=("flow", "dns", "proxy"))
    p_ingest.add_argument("paths", nargs="+", help="raw capture/log files")

    p_watch = sub.add_parser(
        "watch", help="watch a landing directory and ingest new files; "
                      "--procs fans out over worker processes (run the "
                      "same command on N hosts sharing the directory to "
                      "scale out)")
    _add_common(p_watch)
    p_watch.add_argument("datatype", choices=("flow", "dns", "proxy"))
    p_watch.add_argument("landing_dir")
    p_watch.add_argument("--procs", type=int, default=1,
                         help="worker processes (1 = in-process watcher)")
    p_watch.add_argument("--max-seconds", type=float, default=None,
                         help="stop after this long (default: forever)")
    p_watch.add_argument("--drain", action="store_true",
                         help="exit once a poll finds nothing to claim")

    p_stream = sub.add_parser(
        "stream", help="streaming scoring: online-VB model updated and "
                       "scored per ingest minibatch (one file = one batch)")
    _add_common(p_stream)
    p_stream.add_argument("datatype", choices=("flow", "dns", "proxy"))
    p_stream.add_argument("paths", nargs="+", help="raw telemetry files, "
                          "consumed in order as minibatches")
    p_stream.add_argument("--buckets", type=int, default=1 << 15,
                          help="hashed vocabulary size (static V)")
    p_stream.add_argument("--epochs", type=int, default=1,
                          help="replay the file list N times (burn-in)")
    p_stream.add_argument("--superstep", type=int, default=None,
                          metavar="S",
                          help="chain S minibatch updates (E-step + "
                               "lambda step + scoring) in ONE jitted "
                               "dispatch, winners fetched once per "
                               "superstep (pipeline.stream_superstep; "
                               "0/1 = per-batch)")
    p_stream.add_argument("--prefetch-depth", type=int, default=None,
                          metavar="K",
                          help="host pipeline depth: decode+convert up "
                               "to K batches ahead of the device step "
                               "(pipeline.stream_prefetch_depth)")
    p_stream.add_argument("--prefetch-mode", default=None,
                          choices=("auto", "thread", "process"),
                          help="where the host stage runs; auto "
                               "measures conversion wall vs pickle "
                               "round-trip on the first batch "
                               "(pipeline.stream_prefetch_mode)")
    p_stream.add_argument("--fault-plan", default=None, metavar="PLAN",
                          help="chaos drill: declarative fault plan, e.g. "
                               "'stream:batch@3=raise' (docs/ROBUSTNESS.md)")

    p_oa = sub.add_parser(
        "oa", help="operational analytics: enrich scored results for the UI")
    _add_common(p_oa)
    p_oa.add_argument("date", help="day to process, YYYY-MM-DD")
    p_oa.add_argument("datatype", choices=("flow", "dns", "proxy"))

    p_serve = sub.add_parser(
        "serve", help="serve the analyst dashboards + feedback endpoint "
                      "(the reference's notebook file server on :8889)")
    _add_common(p_serve)
    p_serve.add_argument("--port", type=int, default=8889)
    p_serve.add_argument("--host", default="127.0.0.1")
    p_serve.add_argument("--models-dir", default=None,
                         help="fitted-model bank root for the /score "
                              "endpoint (serving.models_dir; default "
                              "<store.root>/models — populate with "
                              "`onix score ... -s serving.save_fitted"
                              "=true`)")
    p_serve.add_argument("--bank-capacity", type=int, default=None,
                         help="resident tenants per bank shape class; "
                              "larger banks LRU-evict at request "
                              "boundaries (serving.bank_capacity)")

    p_label = sub.add_parser(
        "label", help="label OA results by rank (headless analyst feedback; "
                      "the dashboard Save button does the same via POST)")
    _add_common(p_label)
    p_label.add_argument("date", help="day, YYYY-MM-DD")
    p_label.add_argument("datatype", choices=("flow", "dns", "proxy"))
    p_label.add_argument("ranks", type=int, nargs="+",
                         help="dashboard rank numbers to label")
    p_label.add_argument("--label", type=int, required=True,
                         choices=(1, 2, 3),
                         help="1 high threat, 2 medium, 3 benign (only "
                              "benign rows bias the next model run)")

    p_setup = sub.add_parser(
        "setup", help="create the store layout and archive the config "
                      "(the oni-setup equivalent; idempotent)")
    _add_common(p_setup)

    p_demo = sub.add_parser(
        "demo", help="one-command end-to-end demo: synthesize the "
                     "2016-07-08 day, ingest, score, enrich, serve")
    _add_common(p_demo)
    p_demo.add_argument("--events", type=int, default=20000,
                        help="synthetic events per datatype")
    p_demo.add_argument("--generator", choices=("mixture", "sessions"),
                        default="mixture",
                        help="telemetry source: role-mixture synth or "
                             "the independent session/state-machine "
                             "generator")
    p_demo.add_argument("--serve", action="store_true",
                        help="serve the dashboards when done")
    p_demo.add_argument("--port", type=int, default=8889)

    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    cfg = load_config(args.config, args.overrides)

    # r18: route the telemetry layer (enablement, sampling, the
    # flight-recorder dump dir) from the resolved config for EVERY
    # command — a chaos drill on `onix score --fault-plan ...` must
    # land its postmortem under <store.root>/telemetry, not count an
    # unrouted dump.
    from onix.utils import telemetry
    telemetry.apply_config(cfg.telemetry)

    if args.command in ("score", "stream", "demo"):
        # Device-touching commands: persist compiled programs so daily
        # runs never re-pay cold-compile (obs.enable_compile_cache).
        from onix.utils.obs import enable_compile_cache
        import pathlib
        enable_compile_cache(
            pathlib.Path(cfg.store.checkpoint_dir) / "jax_cache")

    if args.command == "config":
        print(cfg.to_json())
        return 0

    if args.command == "score":
        cfg.pipeline.date = args.date
        cfg.pipeline.datatype = args.datatype
        if args.tol is not None:
            cfg.pipeline.tol = args.tol
        if args.max_results is not None:
            cfg.pipeline.max_results = args.max_results
        cfg.validate()          # re-check: flags bypass load_config's pass
        if args.fault_inject is not None:
            if args.engine != "gibbs":
                raise SystemExit(
                    "--fault-inject is only wired to the gibbs engine; "
                    f"a {args.engine} drill would silently do nothing")
            import os
            os.environ["ONIX_FAULT_SWEEP"] = str(args.fault_inject)
        if args.fault_plan is not None:
            from onix.utils import faults
            faults.install_plan(args.fault_plan)    # parse errors exit now
        from onix.pipelines.run import run_scoring
        return run_scoring(cfg, engine=args.engine)

    if args.command == "ingest":
        from onix.ingest.run import run_ingest
        return run_ingest(cfg, args.datatype, args.paths)

    if args.command == "watch":
        if args.procs > 1:
            from onix.ingest.mpingest import run_workers
            stats = run_workers(cfg, args.datatype, args.landing_dir,
                                n_procs=args.procs,
                                max_seconds=args.max_seconds,
                                idle_exit=args.drain)
            print(f"onix watch: {stats['files']} files, {stats['rows']} "
                  f"rows, {stats['errors']} errors, "
                  f"{stats.get('retries', 0)} retries, "
                  f"{stats.get('quarantined', 0)} quarantined, "
                  f"{stats.get('salvaged', 0)} salvaged "
                  f"({stats['workers']} workers)")
            return 1 if stats["errors"] else 0
        import time as time_mod
        from onix.ingest.watcher import IngestWatcher
        w = IngestWatcher(cfg, args.datatype, args.landing_dir,
                          require_stable=not args.drain)
        if args.drain:
            # Drain until nothing dispatches AND no failed file is
            # still inside its retry budget — a drain must carry every
            # failure to its salvage-or-quarantine verdict, not abandon
            # it mid-backoff for the next invocation.
            t0 = time_mod.monotonic()
            while True:
                dispatched = w.poll_once()
                if not dispatched and not w.pending_retries():
                    break
                if (args.max_seconds is not None
                        and time_mod.monotonic() - t0 > args.max_seconds):
                    break
                if not dispatched:
                    time_mod.sleep(min(w.poll_interval, 0.2))
        else:
            w.run(max_seconds=args.max_seconds)
        print(f"onix watch: {w.stats['files']} files, {w.stats['rows']} "
              f"rows, {w.stats['errors']} errors, "
              f"{w.stats['retries']} retries, "
              f"{w.stats['quarantined']} quarantined, "
              f"{w.stats['salvaged']} salvaged")
        return 1 if w.stats["errors"] else 0

    if args.command == "stream":
        if args.fault_plan is not None:
            from onix.utils import faults
            faults.install_plan(args.fault_plan)
        if args.superstep is not None:
            cfg.pipeline.stream_superstep = args.superstep
        if args.prefetch_depth is not None:
            cfg.pipeline.stream_prefetch_depth = args.prefetch_depth
        if args.prefetch_mode is not None:
            cfg.pipeline.stream_prefetch_mode = args.prefetch_mode
        cfg.validate()          # re-check: flags bypass load_config's pass
        from onix.pipelines.streaming import run_stream
        return run_stream(cfg, args.datatype, args.paths,
                          n_buckets=args.buckets, epochs=args.epochs)

    if args.command == "oa":
        from onix.oa.engine import run_oa
        return run_oa(cfg, args.date, args.datatype)

    if args.command == "serve":
        if args.models_dir is not None:
            cfg.serving.models_dir = args.models_dir
        if args.bank_capacity is not None:
            cfg.serving.bank_capacity = args.bank_capacity
        cfg.validate()          # re-check: flags bypass load_config's pass
        from onix.oa.serve import run_serve
        return run_serve(cfg, port=args.port, host=args.host)

    if args.command == "setup":
        from onix.setup_cmd import run_setup
        return run_setup(cfg)

    if args.command == "demo":
        from onix.setup_cmd import run_demo
        return run_demo(cfg, n_events=args.events, serve=args.serve,
                        port=args.port, generator=args.generator)

    if args.command == "label":
        from onix.oa.feedback import label_by_rank
        path = label_by_rank(cfg, args.datatype, args.date, args.ranks,
                             args.label)
        print(f"onix label: {len(args.ranks)} rows -> {path}")
        return 0

    return 2


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:     # e.g. `onix config | head`
        sys.exit(0)
