"""The contract passes. Each is a pure function over the shared
`AnalysisContext`; registration order is report order.

Every pass reads its CONTRACT from the tree itself (ENV_REGISTRY in
config.py, COUNTER_NAMESPACES in obs.py, FINGERPRINT_FIELDS /
FINGERPRINT_EXEMPT in checkpoint.py, per-class GUARDED_BY maps, the
ROBUSTNESS.md site table) — parsed from the AST, never imported, so
the linter works on a tree too broken to import and fixture tests can
stand up miniature trees under tests/analysis_fixtures/.
"""

from __future__ import annotations

import ast
import re

from onix.analysis.core import AnalysisContext, Finding, SourceFile, register

# ---------------------------------------------------------------------------
# Shared AST helpers.
# ---------------------------------------------------------------------------


def _dotted(node: ast.AST) -> str | None:
    """Render `a.b.c` chains; None for anything fancier."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _module_dict(ctx: AnalysisContext, var_name: str
                 ) -> tuple[SourceFile | None, dict[str, ast.AST],
                            dict[str, int]]:
    """Find a module-level `NAME = {literal dict}` anywhere in scope.
    Returns (file, key -> value node, key -> key line)."""
    for sf in ctx.files:
        for node in sf.tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == var_name \
                        and isinstance(getattr(node, "value", None), ast.Dict):
                    values: dict[str, ast.AST] = {}
                    lines: dict[str, int] = {}
                    for k, v in zip(node.value.keys, node.value.values):
                        ks = _str_const(k)
                        if ks is not None:
                            values[ks] = v
                            lines[ks] = k.lineno
                    return sf, values, lines
    return None, {}, {}


def _enclosing_functions(sf: SourceFile, node: ast.AST):
    for anc in sf.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield anc


def _contains_call(tree: ast.AST, name: str) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            called = fn.id if isinstance(fn, ast.Name) else \
                fn.attr if isinstance(fn, ast.Attribute) else None
            if called == name:
                return True
    return False


# ---------------------------------------------------------------------------
# Pass 1: exception discipline (the r9 lint, promoted from
# tests/test_faults.py — the thin tier-1 wrapper there still runs it).
# ---------------------------------------------------------------------------

#: Call names that make an except-Exception handler "visible": loggers,
#: obs counters, run-log emits, HTTP error responses, stdout.
VISIBLE_CALLS = {"exception", "warning", "error", "info", "debug",
                 "inc", "emit", "send_error", "warn", "print", "skip"}


def handler_is_visible(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else "")
            if name in VISIBLE_CALLS:
                return True
    return False


@register("excepts", "bare/broad except handlers must log, count, or "
          "re-raise")
def check_excepts(ctx: AnalysisContext) -> list[Finding]:
    out = []
    for sf in ctx.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            t = node.type
            names: list[str] = []
            if t is None:                       # bare `except:`
                names = ["BaseException"]
            elif isinstance(t, ast.Name):
                names = [t.id]
            elif isinstance(t, ast.Tuple):
                names = [e.id for e in t.elts if isinstance(e, ast.Name)]
            if not any(n in ("Exception", "BaseException") for n in names):
                continue
            if not handler_is_visible(node):
                out.append(Finding(
                    "excepts", sf.rel, node.lineno,
                    "silent except-Exception handler: log, counters.inc, "
                    "or raise (a swallowed exception in a resilience-"
                    "hardened pipeline is indistinguishable from silent "
                    "data loss)"))
    return out


# ---------------------------------------------------------------------------
# Pass 2: env registry — every literal ONIX_* env use must be declared
# in config.ENV_REGISTRY; dead declarations are flagged too.
# ---------------------------------------------------------------------------

_ENV_NAME_RE = re.compile(r"^_?ONIX_[A-Z0-9_]+$")


def _env_uses(sf: SourceFile):
    """Yield (name, line) for every literal env access: environ.get /
    .pop / .setdefault, os.getenv, environ[...] reads AND writes, and
    `env_var=` keywords (config.resolve_form_gate reads the env
    itself)."""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) \
                    and fn.attr in ("get", "pop", "setdefault") \
                    and (_dotted(fn.value) or "").endswith("environ") \
                    and node.args:
                name = _str_const(node.args[0])
                if name:
                    yield name, node.lineno
            called = fn.attr if isinstance(fn, ast.Attribute) else \
                fn.id if isinstance(fn, ast.Name) else None
            if called == "getenv" and node.args:
                name = _str_const(node.args[0])
                if name:
                    yield name, node.lineno
            for kw in node.keywords:
                if kw.arg == "env_var":
                    name = _str_const(kw.value)
                    if name:
                        yield name, node.lineno
        elif isinstance(node, ast.Subscript) \
                and (_dotted(node.value) or "").endswith("environ"):
            name = _str_const(node.slice)
            if name:
                yield name, node.lineno


@register("envs", "literal ONIX_* env accesses must be declared in "
          "config.ENV_REGISTRY")
def check_envs(ctx: AnalysisContext) -> list[Finding]:
    reg_sf, reg, reg_lines = _module_dict(ctx, "ENV_REGISTRY")
    out = []
    used: set[str] = set()
    for sf in ctx.files:
        for name, line in _env_uses(sf):
            if not _ENV_NAME_RE.match(name):
                continue
            used.add(name)
            if name not in reg:
                out.append(Finding(
                    "envs", sf.rel, line,
                    f"env {name} is not declared in config.ENV_REGISTRY "
                    "(name, type, one-line doc) — an undocumented knob "
                    "is an unreviewable behavior switch"))
    for name, line in sorted(reg_lines.items()):
        if name not in used:
            out.append(Finding(
                "envs", reg_sf.rel, line,
                f"ENV_REGISTRY declares {name} but nothing in scope "
                "reads it — dead declaration (delete it, or the reader "
                "moved out of the linted tree)"))
    return out


# ---------------------------------------------------------------------------
# Pass 3: counter namespaces — literal counter keys and f-string
# prefixes must open with a namespace declared in
# obs.COUNTER_NAMESPACES; a typo'd namespace silently never aggregates.
# ---------------------------------------------------------------------------

_COUNTER_METHODS = {"inc", "note_max", "get"}
_PREFIX_METHODS = {"snapshot", "reset"}


def _counter_receiver(fn: ast.Attribute) -> bool:
    dotted = _dotted(fn.value) or ""
    last = dotted.rsplit(".", 1)[-1]
    return last in ("counters", "_counters")


def _key_of(arg: ast.AST) -> tuple[str | None, bool]:
    """(leading literal, is_dynamic_tail). A plain variable key returns
    (None, False) — out of the rule's scope by design (the forwarding
    loops that relay worker counter deltas)."""
    s = _str_const(arg)
    if s is not None:
        return s, False
    if isinstance(arg, ast.JoinedStr) and arg.values:
        first = arg.values[0]
        lead = _str_const(first)
        if lead is not None:
            return lead, True
        return "", True     # f-string opening with a placeholder
    return None, False


@register("counters", "literal counter keys / f-string prefixes must "
          "match obs.COUNTER_NAMESPACES")
def check_counters(ctx: AnalysisContext) -> list[Finding]:
    ns_sf, ns, ns_lines = _module_dict(ctx, "COUNTER_NAMESPACES")
    out = []
    used_ns: set[str] = set()

    def validate(sf, line, key, what):
        head = key.split(".", 1)[0]
        if head in ns:
            used_ns.add(head)
            return
        out.append(Finding(
            "counters", sf.rel, line,
            f"{what} {key!r} opens with undeclared namespace {head!r} "
            "(declare it in obs.COUNTER_NAMESPACES, or fix the typo — "
            "a misnamespaced counter silently never aggregates)"))

    for sf in ctx.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) and _counter_receiver(fn) \
                    and node.args:
                if fn.attr in _COUNTER_METHODS:
                    key, dynamic = _key_of(node.args[0])
                    if key is None:
                        continue
                    if dynamic and not key:
                        out.append(Finding(
                            "counters", sf.rel, node.lineno,
                            "counter key is an f-string with no literal "
                            "namespace prefix — unverifiable statically "
                            "(exempt with the namespace contract, or "
                            "hoist the prefix)"))
                        continue
                    validate(sf, node.lineno, key, f"counters.{fn.attr} key")
                elif fn.attr in _PREFIX_METHODS:
                    key = _str_const(node.args[0])
                    if key:
                        validate(sf, node.lineno, key,
                                 f"counters.{fn.attr} prefix")
            # retry_call(..., counter_prefix="x.y") feeds
            # f"{prefix}.retries" — the literal prefix is checkable at
            # the call site even though the inc itself is dynamic.
            for kw in node.keywords:
                if kw.arg == "counter_prefix":
                    key = _str_const(kw.value)
                    if key:
                        validate(sf, node.lineno, key, "counter_prefix")
    for name, line in sorted(ns_lines.items()):
        if name not in used_ns:
            out.append(Finding(
                "counters", ns_sf.rel, line,
                f"COUNTER_NAMESPACES declares {name!r} but no literal "
                "counter key in scope uses it — dead namespace"))
    return out


# ---------------------------------------------------------------------------
# Pass 3b: span registry — literal span names opened on the tracer
# (TRACER.span / TRACER.observe, utils/telemetry.py) must be declared
# in telemetry.SPAN_REGISTRY; dead declarations are flagged, and a
# non-literal span name is a finding (exempt it, or hoist the literal)
# — the same discipline as the counters/envs passes, because a typo'd
# span name is a latency series that silently never aggregates.
# ---------------------------------------------------------------------------

_SPAN_RECEIVERS = {"TRACER", "tracer", "_tracer"}
_SPAN_METHODS = {"span", "observe"}


def _span_uses(sf: SourceFile):
    """Yield (name_or_None, line) for every tracer span/observe call:
    name is the literal first argument, or None when dynamic."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute)
                and fn.attr in _SPAN_METHODS):
            continue
        recv = (_dotted(fn.value) or "").rsplit(".", 1)[-1]
        if recv not in _SPAN_RECEIVERS or not node.args:
            continue
        yield _str_const(node.args[0]), node.lineno


@register("spans", "literal span names must be declared in "
          "telemetry.SPAN_REGISTRY; dead declarations flagged")
def check_spans(ctx: AnalysisContext) -> list[Finding]:
    reg_sf, reg, reg_lines = _module_dict(ctx, "SPAN_REGISTRY")
    out = []
    used: set[str] = set()
    for sf in ctx.files:
        for name, line in _span_uses(sf):
            if name is None:
                out.append(Finding(
                    "spans", sf.rel, line,
                    "span name is not a string literal — unverifiable "
                    "statically (hoist the literal and carry the "
                    "dynamic part as span attrs, or exempt with the "
                    "naming contract)"))
                continue
            used.add(name)
            if name not in reg:
                out.append(Finding(
                    "spans", sf.rel, line,
                    f"span {name!r} is not declared in "
                    "telemetry.SPAN_REGISTRY (name -> one-line doc) — "
                    "an undeclared span is a latency series no one can "
                    "find or alert on"))
    for name, line in sorted(reg_lines.items()):
        if name not in used:
            out.append(Finding(
                "spans", reg_sf.rel, line,
                f"SPAN_REGISTRY declares {name!r} but no literal "
                "tracer call opens it — dead declaration (delete it, "
                "or the opener moved out of the linted tree)"))
    return out


# ---------------------------------------------------------------------------
# Pass 4: gate discipline — select_*_form gates and _*_MIN_* crossover
# tables resolve through config.resolve_form_gate, the ONE precedence
# chain (env > explicit > measured > default).
# ---------------------------------------------------------------------------

_SELECT_RE = re.compile(r"^select_\w*_form$")
_TABLE_RE = re.compile(r"^_[A-Z0-9_]*_MIN_[A-Z0-9_]*$")


@register("gates", "select_*_form gates / _*_MIN_* tables must resolve "
          "through config.resolve_form_gate")
def check_gates(ctx: AnalysisContext) -> list[Finding]:
    out = []
    tables: set[str] = set()
    for sf in ctx.files:
        for node in sf.tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and _TABLE_RE.match(t.id) \
                        and isinstance(getattr(node, "value", None), ast.Dict):
                    tables.add(t.id)
    for sf in ctx.files:
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and _SELECT_RE.match(node.name):
                if not _contains_call(node, "resolve_form_gate"):
                    out.append(Finding(
                        "gates", sf.rel, node.lineno,
                        f"{node.name} does not resolve through "
                        "config.resolve_form_gate — a hand-rolled "
                        "precedence chain WILL drift from the other "
                        "gates (env > explicit > measured > default)"))
            name = None
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                name = node.id
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                name = node.attr
            if name in tables:
                ok = any(_contains_call(fn, "resolve_form_gate")
                         for fn in _enclosing_functions(sf, node))
                if not ok:
                    out.append(Finding(
                        "gates", sf.rel, node.lineno,
                        f"crossover table {name} consulted outside a "
                        "resolve_form_gate-resolving gate — measured "
                        "tables feed gates, never ad-hoc branches"))
    return out


# ---------------------------------------------------------------------------
# Pass 5: fingerprint coverage — LDAConfig fields read inside the
# engine modules must be fingerprint-contributing
# (checkpoint.FINGERPRINT_FIELDS) or exempt with a written reason
# (checkpoint.FINGERPRINT_EXEMPT), so the next merge_staleness-class
# knob cannot ship without resume refusal.
# ---------------------------------------------------------------------------

#: The engine modules whose constructors / program builders consume
#: LDAConfig. Matched on rel-path basename so fixture trees can mirror
#: the layout.
ENGINE_BASENAMES = {"lda_gibbs.py", "lda_svi.py", "sharded_gibbs.py",
                    "streaming.py", "model_bank.py", "fleet_gibbs.py"}

#: Receivers whose attribute reads count as LDAConfig-field reads:
#: bare names bound to an LDAConfig, and attribute tails reaching one.
_CFG_NAMES = {"lda", "cfg", "config", "lda_cfg"}
_CFG_ATTRS = {"lda", "cfg", "config", "_lda_eff"}


def _lda_fields(ctx: AnalysisContext) -> set[str]:
    for sf in ctx.files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef) and node.name == "LDAConfig":
                return {s.target.id for s in node.body
                        if isinstance(s, ast.AnnAssign)
                        and isinstance(s.target, ast.Name)}
    return set()


@register("fingerprints", "LDAConfig fields read in engine modules must "
          "join a checkpoint fingerprint or be exempt with a reason")
def check_fingerprints(ctx: AnalysisContext) -> list[Finding]:
    fields = _lda_fields(ctx)
    if not fields:
        return []
    _, contrib, _ = _module_dict(ctx, "FINGERPRINT_FIELDS")
    _, exempt, _ = _module_dict(ctx, "FINGERPRINT_EXEMPT")
    out = []
    for sf in ctx.files:
        if sf.rel.rsplit("/", 1)[-1] not in ENGINE_BASENAMES:
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and node.attr in fields):
                continue
            recv = node.value
            is_cfg = (isinstance(recv, ast.Name) and recv.id in _CFG_NAMES) \
                or (isinstance(recv, ast.Attribute)
                    and recv.attr in _CFG_ATTRS)
            if not is_cfg:
                continue
            if node.attr in contrib or node.attr in exempt:
                continue
            out.append(Finding(
                "fingerprints", sf.rel, node.lineno,
                f"engine reads lda.{node.attr} but the field is neither "
                "in checkpoint.FINGERPRINT_FIELDS nor "
                "checkpoint.FINGERPRINT_EXEMPT — a semantics-changing "
                "knob outside the fingerprint resumes checkpoints into "
                "a silently different chain (the r11/r14 contract)"))
    return out


# ---------------------------------------------------------------------------
# Pass 6: jit/trace hazards — host nondeterminism and implicit device
# syncs inside functions reachable from jit/pallas_call/scan bodies.
# time.time()/np.random inside a traced function CONSTANT-FOLDS at
# trace time: the program runs, and every later call replays the first
# call's "random" values — wrong-but-plausible by construction.
# ---------------------------------------------------------------------------

#: rel-path prefixes of the device hot paths.
TRACE_SCOPES = ("onix/models/", "onix/parallel/", "onix/serving/")

_TRACE_ENTRY_CALLS = {"jit", "pallas_call", "scan", "while_loop",
                      "fori_loop", "cond", "switch", "vmap", "pmap",
                      "shard_map", "remat", "checkpoint"}

_HAZARD_TIME = {"time.time", "time.perf_counter", "time.monotonic",
                "time.time_ns"}


def _hazard_of(node: ast.Call) -> str | None:
    dotted = _dotted(node.func) or ""
    if dotted in _HAZARD_TIME:
        return f"host clock read {dotted}()"
    tail = dotted.rsplit(".", 1)[-1]
    if tail in ("now", "utcnow", "today") and "date" in dotted:
        return f"host clock read {dotted}()"
    # Host RNG only: np.random/numpy.random and the stdlib random
    # module constant-fold at trace time. jax.random is the DEVICE-safe
    # key-stream RNG — the correct tool here, never a hazard.
    if dotted.startswith(("np.random.", "numpy.random.", "random.")):
        return f"host RNG {dotted}()"
    if isinstance(node.func, ast.Attribute) and node.func.attr == "item" \
            and not node.args:
        return ".item() (implicit device sync / host round-trip)"
    return None


def _jit_reachable(sf: SourceFile) -> set[ast.AST]:
    """Function defs reachable from a trace entry in this module:
    jit-decorated defs, defs passed by name to jit/pallas_call/scan/...
    calls, plus the module-local call-graph closure. Approximate by
    design (name-level, module-local) — the exemption comment covers
    the rare false positive."""
    defs: dict[str, list[ast.AST]] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    roots: list[ast.AST] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                d = _dotted(dec if not isinstance(dec, ast.Call)
                            else dec.func) or ""
                names = {d.rsplit(".", 1)[-1]}
                if isinstance(dec, ast.Call):       # partial(jax.jit, ...)
                    names |= {(_dotted(a) or "").rsplit(".", 1)[-1]
                              for a in dec.args}
                if names & {"jit", "pallas_call"}:
                    roots.append(node)
        if isinstance(node, ast.Call):
            called = (_dotted(node.func) or "").rsplit(".", 1)[-1]
            if called in _TRACE_ENTRY_CALLS:
                args = list(node.args) + [kw.value for kw in node.keywords]
                for a in args:
                    if isinstance(a, ast.Name) and a.id in defs:
                        roots.extend(defs[a.id])
                    elif isinstance(a, ast.Lambda):
                        roots.append(a)
    reachable: set[int] = set()
    nodes: list[ast.AST] = []
    stack = list(roots)
    while stack:
        fn = stack.pop()
        if id(fn) in reachable:
            continue
        reachable.add(id(fn))
        nodes.append(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                stack.extend(defs.get(node.func.id, []))
    return set(nodes)


@register("tracehaz", "no host nondeterminism / implicit syncs inside "
          "jit/pallas_call/scan-reachable functions")
def check_tracehaz(ctx: AnalysisContext) -> list[Finding]:
    out = []
    for sf in ctx.files:
        if not sf.rel.startswith(TRACE_SCOPES):
            continue
        for fn in _jit_reachable(sf):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                hazard = _hazard_of(node)
                if hazard:
                    out.append(Finding(
                        "tracehaz", sf.rel, node.lineno,
                        f"{hazard} inside a function reachable from a "
                        "jit/pallas_call/scan body — constant-folds at "
                        "trace time (nondeterminism) or forces a device "
                        "sync in the hot path"))
    return out


# ---------------------------------------------------------------------------
# Pass 7: lock discipline — mutable attributes of threaded classes,
# declared in a class-level GUARDED_BY map, may only be mutated under
# their declared lock (`with self.<lock>:`), turning the races the
# chaos harness can only sample into findings the linter proves absent.
# A method whose CALLERS serialize on the lock carries
# `# lint: holds[<lock>]` on its def line.
# ---------------------------------------------------------------------------

_MUTATORS = {"append", "appendleft", "extend", "insert", "pop", "popleft",
             "popitem", "remove", "discard", "clear", "update", "add",
             "setdefault", "move_to_end", "sort", "reverse"}


def _self_attr_root(node: ast.AST) -> str | None:
    """The `X` of self.X[...]...: peel subscripts/attributes down to an
    Attribute on bare `self`."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _mutations(method: ast.AST):
    """Yield (attr, line) for every mutation of a self attribute in the
    method body: assignments (plain/aug/ann, incl. subscript targets),
    deletes, and mutating method calls."""
    for node in ast.walk(method):
        targets: list[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = list(node.targets)
        for t in targets:
            for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
                attr = _self_attr_root(el)
                if attr is not None:
                    yield attr, node.lineno
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            attr = _self_attr_root(node.func.value)
            if attr is not None:
                yield attr, node.lineno


def _locks_held_at(sf: SourceFile, line: int, method: ast.AST) -> set[str]:
    """Lock attrs held at `line` by lexical `with self.<lock>:` blocks
    inside `method`."""
    held: set[str] = set()
    for node in ast.walk(method):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        body_start = node.body[0].lineno
        body_end = max(getattr(n, "end_lineno", n.lineno)
                       for n in node.body)
        if not (body_start <= line <= body_end):
            continue
        for item in node.items:
            e = item.context_expr
            if isinstance(e, ast.Attribute) \
                    and isinstance(e.value, ast.Name) \
                    and e.value.id == "self":
                held.add(e.attr)
    return held


@register("locks", "GUARDED_BY attributes of threaded classes mutate "
          "only under their declared lock")
def check_locks(ctx: AnalysisContext) -> list[Finding]:
    out = []
    for sf in ctx.files:
        for cls in ast.walk(sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            guarded: dict[str, str] = {}
            for stmt in cls.body:
                if isinstance(stmt, ast.Assign) \
                        and any(isinstance(t, ast.Name)
                                and t.id == "GUARDED_BY"
                                for t in stmt.targets) \
                        and isinstance(stmt.value, ast.Dict):
                    for k, v in zip(stmt.value.keys, stmt.value.values):
                        ks, vs = _str_const(k), _str_const(v)
                        if ks and vs:
                            guarded[ks] = vs
            if not guarded:
                continue
            for method in cls.body:
                if not isinstance(method,
                                  (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if method.name == "__init__":
                    continue    # construction happens-before sharing
                holds = {sf.holds[ln]
                         for ln in (method.lineno, method.lineno - 1)
                         if ln in sf.holds}
                for attr, line in _mutations(method):
                    lock = guarded.get(attr)
                    if lock is None:
                        continue
                    if lock in holds:
                        continue
                    if lock not in _locks_held_at(sf, line, method):
                        out.append(Finding(
                            "locks", sf.rel, line,
                            f"{cls.name}.{method.name} mutates "
                            f"self.{attr} outside `with self.{lock}` "
                            f"(GUARDED_BY declares {attr!r} -> "
                            f"{lock!r}) — an off-lock mutation is a "
                            "data race the chaos harness can only "
                            "sample, never prove absent"))
    return out


# ---------------------------------------------------------------------------
# Pass 8: fault-site / doc drift — every faults.fire(stage, point) call
# site appears in the docs/ROBUSTNESS.md site table and vice versa; the
# generated registry tables in the doc must be current.
# ---------------------------------------------------------------------------

_DOC_SITE_RE = re.compile(r"`([a-z_]+:[a-z_]+)`")


def fire_sites(ctx: AnalysisContext) -> dict[str, tuple[str, int]]:
    """stage:point -> (file, line) for every literal faults.fire call."""
    sites: dict[str, tuple[str, int]] = {}
    for sf in ctx.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            called = fn.attr if isinstance(fn, ast.Attribute) else \
                fn.id if isinstance(fn, ast.Name) else None
            if called != "fire" or len(node.args) < 2:
                continue
            stage = _str_const(node.args[0])
            point = _str_const(node.args[1])
            if stage and point:
                sites.setdefault(f"{stage}:{point}", (sf.rel, node.lineno))
    return sites


def doc_sites(text: str) -> dict[str, int]:
    """stage:point -> first doc line, from markdown TABLE rows only
    (prose mentions don't count as registration)."""
    found: dict[str, int] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        if not line.lstrip().startswith("|"):
            continue
        for m in _DOC_SITE_RE.finditer(line):
            found.setdefault(m.group(1), i)
    return found


@register("faultdocs", "faults.fire sites <-> ROBUSTNESS.md site table; "
          "generated registry tables current")
def check_faultdocs(ctx: AnalysisContext) -> list[Finding]:
    from onix.analysis import docgen

    out = []
    doc_path = ctx.root / "docs" / "ROBUSTNESS.md"
    doc_rel = "docs/ROBUSTNESS.md"
    if not doc_path.exists():
        return [Finding("faultdocs", doc_rel, 1,
                        "docs/ROBUSTNESS.md missing — the fault-site "
                        "table and generated registries live there")]
    text = doc_path.read_text()
    in_doc = doc_sites(text)
    in_code = fire_sites(ctx)
    for site, (rel, line) in sorted(in_code.items()):
        if site not in in_doc:
            out.append(Finding(
                "faultdocs", rel, line,
                f"fault site {site} is wired here but absent from the "
                "docs/ROBUSTNESS.md site table — an undocumented site "
                "is unreachable to the chaos operator"))
    for site, line in sorted(in_doc.items()):
        if site not in in_code:
            out.append(Finding(
                "faultdocs", doc_rel, line,
                f"docs/ROBUSTNESS.md documents fault site {site} but no "
                "faults.fire call wires it — doc drift (the site table "
                "promises injection points that do not exist)"))
    for section in docgen.SECTIONS:
        current = docgen.extract_section(text, section)
        want = docgen.render_section(ctx, section)
        if current is None:
            out.append(Finding(
                "faultdocs", doc_rel, 1,
                f"docs/ROBUSTNESS.md lacks the generated section "
                f"{section!r} (markers `{docgen.begin_marker(section)}` "
                f"/ `{docgen.end_marker(section)}`); run "
                "`python -m onix.analysis --write-docs`"))
        elif current.strip() != want.strip():
            out.append(Finding(
                "faultdocs", doc_rel, 1,
                f"generated section {section!r} in docs/ROBUSTNESS.md "
                "is stale — run `python -m onix.analysis --write-docs`"))
    return out
