"""Generated documentation sections — the env-var registry and the
counter-namespace table render into docs/ROBUSTNESS.md between marker
comments, and the `faultdocs` pass verifies the rendered text is
current, so the doc can never drift from the registries it documents.

`python -m onix.analysis --write-docs` rewrites the sections in place.
Rendering parses the registries from the AST (never imports), same as
every pass.
"""

from __future__ import annotations

import ast
import pathlib

from onix.analysis.core import AnalysisContext
from onix.analysis.passes import _module_dict, _str_const

SECTIONS = ("env-registry", "counter-namespaces", "span-registry")


def begin_marker(section: str) -> str:
    return f"<!-- BEGIN GENERATED: {section} (python -m onix.analysis --write-docs) -->"


def end_marker(section: str) -> str:
    return f"<!-- END GENERATED: {section} -->"


def _env_rows(ctx: AnalysisContext) -> list[tuple[str, str, str]]:
    _, reg, _ = _module_dict(ctx, "ENV_REGISTRY")
    rows = []
    for name, value in sorted(reg.items()):
        typ, doc = "", ""
        if isinstance(value, ast.Tuple) and len(value.elts) == 2:
            typ = _str_const(value.elts[0]) or ""
            doc = _str_const(value.elts[1]) or ""
        rows.append((name, typ, doc))
    return rows


def _counter_rows(ctx: AnalysisContext) -> list[tuple[str, str]]:
    _, ns, _ = _module_dict(ctx, "COUNTER_NAMESPACES")
    return [(name, _str_const(value) or "")
            for name, value in sorted(ns.items())]


def _span_rows(ctx: AnalysisContext) -> list[tuple[str, str]]:
    _, reg, _ = _module_dict(ctx, "SPAN_REGISTRY")
    return [(name, _str_const(value) or "")
            for name, value in sorted(reg.items())]


def render_section(ctx: AnalysisContext, section: str) -> str:
    if section == "env-registry":
        lines = ["| env | type | meaning |", "|---|---|---|"]
        lines += [f"| `{n}` | {t} | {d} |" for n, t, d in _env_rows(ctx)]
        return "\n".join(lines)
    if section == "counter-namespaces":
        lines = ["| namespace | events counted under it |", "|---|---|"]
        lines += [f"| `{n}.*` | {d} |" for n, d in _counter_rows(ctx)]
        return "\n".join(lines)
    if section == "span-registry":
        lines = ["| span | one unit of |", "|---|---|"]
        lines += [f"| `{n}` | {d} |" for n, d in _span_rows(ctx)]
        return "\n".join(lines)
    raise ValueError(f"unknown generated section {section!r}")


def extract_section(text: str, section: str) -> str | None:
    """The current content between the section's markers, or None when
    the markers are absent/unterminated."""
    begin, end = begin_marker(section), end_marker(section)
    i = text.find(begin)
    if i < 0:
        return None
    j = text.find(end, i)
    if j < 0:
        return None
    return text[i + len(begin):j]


def write_docs(ctx: AnalysisContext) -> list[str]:
    """Rewrite every stale generated section in docs/ROBUSTNESS.md.
    Returns the sections actually rewritten."""
    doc_path = ctx.root / "docs" / "ROBUSTNESS.md"
    text = doc_path.read_text()
    written = []
    for section in SECTIONS:
        current = extract_section(text, section)
        if current is None:
            continue        # markers absent: faultdocs reports it
        want = render_section(ctx, section)
        if current.strip() == want.strip():
            continue
        begin, end = begin_marker(section), end_marker(section)
        i = text.find(begin) + len(begin)
        j = text.find(end, i)
        text = text[:i] + "\n" + want + "\n" + text[j:]
        written.append(section)
    if written:
        doc_path.write_text(text)
    return written


def write_docs_at(root: str | pathlib.Path | None = None) -> list[str]:
    return write_docs(AnalysisContext.from_root(root))
