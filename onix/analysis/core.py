"""Analyzer infrastructure: parsed sources, findings, exemptions,
pass registry, and the adoption baseline.

Design rules:

* **Static only.** Files are parsed with `ast`; nothing under analysis
  is imported or executed (the registries the passes compare against —
  ENV_REGISTRY, COUNTER_NAMESPACES, FINGERPRINT_FIELDS, GUARDED_BY —
  are read from the AST too, so linting a broken tree cannot crash on
  an import error in the tree).
* **One parse per file.** Every pass receives the same
  `AnalysisContext`; parsing 90 files once costs ~1 s, parsing them
  eight times would not.
* **Exemptions carry their justification in the code.** A finding is
  suppressed by `# lint: exempt[pass-id] -- why` on its line or the
  line above. An exemption with no justification, or one that
  suppresses nothing, is itself reported — the escape hatch is
  auditable, never a mute button.
* **Baseline = adoption, empty = enforced.** `--baseline` compares
  against a committed findings file so a new rule can land before the
  tree is clean; this repo's baseline is EMPTY (the acceptance bar) —
  every finding is fixed or exempted in place.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
import re

#: Bumped whenever a pass's rules change materially — stamped into
#: bench artifacts (detail.resilience.lint) so an artifact records
#: which contract set the tree was clean under.
ANALYSIS_VERSION = 1

_EXEMPT_RE = re.compile(
    r"#\s*lint:\s*exempt\[(?P<pass>[a-z0-9_-]+)\]\s*(?:--\s*(?P<why>.*))?")
_HOLDS_RE = re.compile(r"#\s*lint:\s*holds\[(?P<lock>\w+)\]")


@dataclasses.dataclass(frozen=True)
class Finding:
    pass_id: str
    path: str           # repo-relative, posix
    line: int           # 1-indexed
    message: str

    @property
    def key(self) -> str:
        """Baseline identity: line numbers drift under unrelated edits,
        so the key is (pass, path, message)."""
        return f"{self.pass_id}|{self.path}|{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"


@dataclasses.dataclass
class Exemption:
    pass_id: str
    line: int
    justification: str
    used: bool = False


class SourceFile:
    """One parsed source file: text, AST, parent links, exemption and
    holds annotations."""

    def __init__(self, abs_path: pathlib.Path, rel_path: str):
        self.abs_path = abs_path
        self.rel = rel_path
        self.text = abs_path.read_text()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=str(abs_path))
        self._parents: dict[int, ast.AST] | None = None
        # line -> [Exemption]; a line may exempt several passes.
        self.exemptions: dict[int, list[Exemption]] = {}
        # line -> lock name asserted held (methods whose callers
        # serialize on the lock — the locks pass honors it on `def`s).
        self.holds: dict[int, str] = {}
        # Annotations come from real COMMENT tokens, never raw lines —
        # a docstring or error message QUOTING the exemption syntax
        # must neither suppress findings nor register as stale.
        for line_no, comment in self._comments():
            m = _EXEMPT_RE.search(comment)
            if m:
                self.exemptions.setdefault(line_no, []).append(
                    Exemption(m.group("pass"), line_no,
                              (m.group("why") or "").strip()))
            h = _HOLDS_RE.search(comment)
            if h:
                self.holds[line_no] = h.group("lock")

    def _comments(self):
        import io
        import tokenize
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    yield tok.start[0], tok.string
        except (tokenize.TokenError, IndentationError):
            # ast.parse succeeded, so this is tokenize-only noise;
            # comments past the error point are simply not annotations.
            return

    def parent(self, node: ast.AST) -> ast.AST | None:
        if self._parents is None:
            self._parents = {}
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self._parents[id(child)] = parent
        return self._parents.get(id(node))

    def ancestors(self, node: ast.AST):
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def exemption_for(self, pass_id: str, line: int) -> Exemption | None:
        """The exemption covering `line` for `pass_id`: same line, or
        the line directly above (a comment-only line)."""
        for ln in (line, line - 1):
            for ex in self.exemptions.get(ln, ()):
                if ex.pass_id == pass_id:
                    return ex
        return None


def default_targets(root: pathlib.Path) -> list[pathlib.Path]:
    """The analyzer's scope — the same file set the r9 lint grew to
    cover: ALL of onix/ plus the harness code outside the package
    (bench.py, scripts/*.py). tests/ are deliberately out: they pin
    envs and poke private tables as part of their job."""
    files = sorted((root / "onix").rglob("*.py"))
    bench = root / "bench.py"
    if bench.exists():
        files.append(bench)
    files += sorted((root / "scripts").glob("*.py"))
    return files


class AnalysisContext:
    def __init__(self, root: pathlib.Path, files: list[SourceFile]):
        self.root = root
        self.files = files
        self.by_rel = {f.rel: f for f in files}

    @classmethod
    def from_root(cls, root: str | pathlib.Path | None = None,
                  paths: list[str | pathlib.Path] | None = None
                  ) -> "AnalysisContext":
        if root is None:
            # onix/analysis/core.py -> repo root two levels up from the
            # package dir — UNLESS the package is pip-installed into
            # site-packages (no docs/, bench.py, or scripts/ siblings
            # there), in which case `onix-lint` run from a repo
            # checkout must lint the CHECKOUT, not the installed copy:
            # fall back to cwd when it looks like the repo and the
            # package-derived root does not.
            pkg_root = pathlib.Path(__file__).resolve().parents[2]
            root = pkg_root
            if not (pkg_root / "docs" / "ROBUSTNESS.md").exists():
                cwd = pathlib.Path.cwd()
                if (cwd / "onix").is_dir() \
                        and (cwd / "docs" / "ROBUSTNESS.md").exists():
                    root = cwd
        root = pathlib.Path(root)
        targets: list[pathlib.Path] = []
        if paths:
            for p in paths:
                p = pathlib.Path(p)
                if not p.is_absolute():
                    p = root / p
                if p.is_dir():
                    targets += sorted(p.rglob("*.py"))
                else:
                    targets.append(p)
        else:
            targets = default_targets(root)
        files = []
        for t in targets:
            try:
                rel = str(t.resolve().relative_to(root.resolve()).as_posix())
            except ValueError:
                rel = str(t)
            files.append(SourceFile(t, rel))
        return cls(root, files)


# -- pass registry ----------------------------------------------------------

#: pass_id -> (fn, one-line doc). Passes self-register via @register.
PASSES: dict[str, tuple] = {}


def register(pass_id: str, doc: str):
    def deco(fn):
        PASSES[pass_id] = (fn, doc)
        return fn
    return deco


def run_passes(ctx: AnalysisContext,
               only: list[str] | None = None) -> list[Finding]:
    """Run every registered pass (or `only`), apply exemptions, and
    report unused/justification-less exemptions. Returns findings
    sorted by (path, line)."""
    from onix.analysis import passes as _passes  # noqa: F401 (registers)

    selected = PASSES if only is None else {
        k: v for k, v in PASSES.items() if k in only}
    unknown = set(only or ()) - set(PASSES)
    if unknown:
        raise ValueError(f"unknown passes: {sorted(unknown)} "
                         f"(have {sorted(PASSES)})")
    raw: list[Finding] = []
    for pass_id, (fn, _doc) in selected.items():
        raw.extend(fn(ctx))
    kept: list[Finding] = []
    for f in raw:
        sf = ctx.by_rel.get(f.path)
        ex = sf.exemption_for(f.pass_id, f.line) if sf is not None else None
        if ex is None:
            kept.append(f)
        else:
            ex.used = True
    # The exemption mechanism polices itself: empty justifications and
    # exemptions that no longer suppress anything are findings (only
    # for the passes that actually ran, so --passes stays composable).
    ran = set(selected)
    for sf in ctx.files:
        for exs in sf.exemptions.values():
            for ex in exs:
                if ex.pass_id not in ran:
                    continue
                if not ex.justification:
                    kept.append(Finding(
                        "exemptions", sf.rel, ex.line,
                        f"exempt[{ex.pass_id}] carries no justification "
                        "(write `# lint: exempt[...] -- why`)"))
                elif not ex.used:
                    kept.append(Finding(
                        "exemptions", sf.rel, ex.line,
                        f"exempt[{ex.pass_id}] suppresses nothing — "
                        "stale exemption, delete it"))
    return sorted(kept, key=lambda f: (f.path, f.line, f.pass_id))


# -- baseline ---------------------------------------------------------------

def load_baseline(path: str | pathlib.Path) -> dict[str, int]:
    """A committed findings multiset (key -> count) for incremental
    adoption of a new pass. Missing file = empty baseline."""
    p = pathlib.Path(path)
    if not p.exists():
        return {}
    data = json.loads(p.read_text())
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def write_baseline(path: str | pathlib.Path,
                   findings: list[Finding]) -> None:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    pathlib.Path(path).write_text(json.dumps(
        {"analysis_version": ANALYSIS_VERSION,
         "findings": dict(sorted(counts.items()))}, indent=2) + "\n")


def new_findings(findings: list[Finding],
                 baseline: dict[str, int]) -> list[Finding]:
    """Findings beyond the baseline's per-key budget — the non-zero-exit
    set. A fixed finding never hides a new one of the same key."""
    budget = dict(baseline)
    out = []
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
        else:
            out.append(f)
    return out
