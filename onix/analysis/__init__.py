"""Contract linter — registry-driven multi-pass static analysis.

Eleven PRs grew onix around a handful of load-bearing conventions:
form gates resolve through `config.resolve_form_gate`, semantics-
changing knobs join checkpoint fingerprints (the r11/r14 resume-refusal
contract), every `ONIX_*` env and `faults.fire` site is documented,
counters land in a declared namespace, and no exception is swallowed
silently. Until r17 only ONE of those conventions (the r9
except-swallow rule) was machine-checked, as a single test buried in
tests/test_faults.py. Staleness- and parallelism-heavy designs like
AD-LDA (arxiv 0909.4603) and streaming Gibbs (arxiv 1601.01142) are
exactly the kind where a knob that silently misses the fingerprint or
a shared field mutated off-lock produces wrong-but-plausible results —
so every discipline is now a PASS over the AST, run by tier-1
(tests/test_analysis.py) and by `python -m onix.analysis` /
`onix-lint` (scripts/lint.sh bundles the native sanitizer test).

Passes (onix/analysis/passes.py; each has a fixture test proving it
fires on a violation and stays silent on the fixed form):

  excepts       bare/broad except handlers must log, count, or re-raise
  envs          literal ONIX_* env reads must be declared in
                config.ENV_REGISTRY; dead declarations flagged
  counters      literal counter keys / f-string prefixes must open with
                a namespace declared in obs.COUNTER_NAMESPACES
  spans         literal span names opened on the tracer must be
                declared in telemetry.SPAN_REGISTRY (r18); dead
                declarations and non-literal names flagged
  gates         select_*_form gates and _*_MIN_* crossover tables must
                resolve through config.resolve_form_gate
  fingerprints  LDAConfig fields read inside the engine modules must be
                fingerprint-contributing (checkpoint.FINGERPRINT_FIELDS)
                or exempt with a justification
  tracehaz      host nondeterminism / implicit device syncs inside
                functions reachable from jit/pallas_call/scan bodies
  locks         GUARDED_BY-declared attributes of threaded classes may
                only be mutated under their declared lock
  faultdocs     faults.fire sites <-> the ROBUSTNESS.md site table, and
                the generated registry tables must be current

Exemption mechanism: `# lint: exempt[pass-id] -- justification` on the
finding's line (or the line above); `# lint: holds[lock]` on a `def`
line asserts the method's callers hold the lock. Exemptions without a
justification, and exemptions that suppress nothing, are themselves
findings — the escape hatch cannot rot into a blanket mute.
"""

from onix.analysis.core import (  # noqa: F401
    ANALYSIS_VERSION,
    AnalysisContext,
    Finding,
    default_targets,
    load_baseline,
    new_findings,
    run_passes,
)


def lint_status(root=None) -> dict:
    """One-call summary for artifact stamping (bench detail.resilience):
    the analyzer version and the finding count over the default scope.
    A lint-clean tree stamps {"version": N, "findings": 0}."""
    ctx = AnalysisContext.from_root(root)
    found = run_passes(ctx)
    return {"version": ANALYSIS_VERSION, "findings": len(found)}
