"""CLI: `python -m onix.analysis` / the `onix-lint` console script.

Exit codes: 0 = clean (no findings beyond the baseline), 1 = new
findings, 2 = usage error. The committed posture of this repo is an
EMPTY baseline — every finding fixed or exempted in code — so plain
`onix-lint` is the enforcement gate (scripts/lint.sh bundles it with
the native sanitizer test).
"""

from __future__ import annotations

import argparse
import sys

from onix.analysis import core


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="onix-lint",
        description="onix contract linter (registry-driven multi-pass "
                    "AST static analysis)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: onix/, "
                         "bench.py, scripts/)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: inferred from the package)")
    ap.add_argument("--passes", default=None,
                    help="comma-separated pass ids (default: all)")
    ap.add_argument("--baseline", default=None,
                    help="findings baseline JSON for incremental adoption; "
                         "only NEW findings fail the run")
    ap.add_argument("--write-baseline", default=None, metavar="PATH",
                    help="write the current findings as a baseline and exit 0")
    ap.add_argument("--write-docs", action="store_true",
                    help="regenerate the generated sections in "
                         "docs/ROBUSTNESS.md from the registries")
    ap.add_argument("--list-passes", action="store_true")
    args = ap.parse_args(argv)

    if args.list_passes:
        from onix.analysis import passes as _passes  # noqa: F401
        for pass_id, (_fn, doc) in core.PASSES.items():
            print(f"{pass_id:14s} {doc}")
        return 0

    try:
        ctx = core.AnalysisContext.from_root(args.root, args.paths or None)
    except (OSError, SyntaxError) as e:
        print(f"onix-lint: cannot load sources: {e}", file=sys.stderr)
        return 2

    if args.write_docs:
        from onix.analysis import docgen
        for section in docgen.write_docs(ctx):
            print(f"rewrote generated section {section!r} in "
                  "docs/ROBUSTNESS.md")

    only = args.passes.split(",") if args.passes else None
    try:
        findings = core.run_passes(ctx, only=only)
    except ValueError as e:
        print(f"onix-lint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        core.write_baseline(args.write_baseline, findings)
        print(f"wrote {len(findings)} finding(s) to {args.write_baseline}")
        return 0

    baseline = core.load_baseline(args.baseline) if args.baseline else {}
    new = core.new_findings(findings, baseline)
    for f in new:
        print(f.render())
    known = len(findings) - len(new)
    tail = f" ({known} baselined)" if known else ""
    print(f"onix-lint: {len(new)} finding(s){tail}, "
          f"{len(ctx.files)} file(s), analysis v{core.ANALYSIS_VERSION}")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
