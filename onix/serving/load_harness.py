"""Mixed-tenant load harness for the model bank (r12).

Replays a skewed (Zipf) tenant traffic stream through `BankService`
and reports the serving numbers the bank is judged on: aggregate
events/s, per-request-batch latency p50/p99, winner-cache hit rate,
and residency churn (admits/evicts) — plus the two proofs:

* **parity** — every scored request's bottom-M winners bit-identical
  to the single-tenant `top_suspicious` path run per request;
* **residency identity** — a capacity-capped replay produces winners
  identical to an uncapped replay of the same stream (eviction happens
  only at request-batch boundaries, so it can never change a score).

`scripts/exp_model_bank.py` is the CLI wrapper that adds interleaved
sequential-vs-banked timing arms and writes the measured artifact
(docs/BANK_r12_cpu.json); tests/test_model_bank_smoke.py runs this
harness at a tiny shape in tier-1 so it cannot rot between TPU tunnel
windows (the test_fit_gap_smoke discipline).
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from onix.serving.model_bank import (BankService, ModelBank, ScoreRequest,
                                     TenantModel)
from onix.utils.obs import counters


@dataclasses.dataclass
class HarnessSpec:
    """Shape of one harness run. Defaults are the acceptance shape
    (64 resident tenants); the tier-1 smoke shrinks everything."""
    n_tenants: int = 64
    n_docs: int = 2048          # per-tenant document count (D)
    n_vocab: int = 1024         # per-tenant product-vocabulary size (V)
    n_topics: int = 20
    n_requests: int = 256       # total requests in the replay stream
    events_per_request: int = 2048
    n_windows: int = 4          # windows per tenant; repeats -> cache hits
    #                             (0 = uncached stream: every request a
    #                             fresh window=None event set — the pure
    #                             scoring-throughput arm)
    zipf_a: float = 1.2         # tenant popularity skew
    batch_requests: int = 64    # service batching (requests per score())
    capacity: int = 0           # resident cap; 0 = all tenants resident
    tol: float = 1.0
    max_results: int = 100
    seed: int = 0


def make_tenants(spec: HarnessSpec) -> dict[str, TenantModel]:
    """Synthetic per-tenant (θ, φ) tables — Dirichlet rows, one shared
    shape class (the common case: tenants of one datatype × day ladder
    into the same pow2 bucket)."""
    rng = np.random.default_rng(spec.seed)
    out = {}
    for t in range(spec.n_tenants):
        theta = rng.dirichlet(np.full(spec.n_topics, 0.5),
                              size=spec.n_docs).astype(np.float32)
        phi = rng.dirichlet(np.full(spec.n_topics, 0.5),
                            size=spec.n_vocab).astype(np.float32)
        out[f"t{t:04d}"] = TenantModel(theta, phi)
    return out


def make_stream(spec: HarnessSpec) -> list[ScoreRequest]:
    """Zipf-skewed request stream. Each (tenant, window) pair's event
    set is generated ONCE and reused on every repeat — the winner
    cache's immutable-window contract, and what real replay traffic
    (dashboards re-opening a scored day) looks like."""
    rng = np.random.default_rng(spec.seed + 1)
    ranks = (rng.zipf(spec.zipf_a, spec.n_requests) - 1) % spec.n_tenants
    # Scatter ranks so hot tenants aren't id-contiguous (same trick as
    # bench._zipf_pairs).
    tenant_ids = (ranks * 2654435761) % spec.n_tenants
    events: dict[tuple[int, int], tuple[np.ndarray, np.ndarray]] = {}
    stream = []

    def draw(n):
        return (rng.integers(0, spec.n_docs, n).astype(np.int32),
                rng.integers(0, spec.n_vocab, n).astype(np.int32))

    for t in tenant_ids:
        if spec.n_windows:
            w = int(rng.integers(spec.n_windows))
            key = (int(t), w)
            if key not in events:
                events[key] = draw(spec.events_per_request)
            d, wd = events[key]
            window = f"w{w}"
        else:
            d, wd = draw(spec.events_per_request)
            window = None
        stream.append(ScoreRequest(tenant=f"t{int(t):04d}", doc_ids=d,
                                   word_ids=wd, window=window))
    return stream


def build_service(spec: HarnessSpec, models: dict[str, TenantModel],
                  form: str = "auto", serve_form: str = "auto"
                  ) -> BankService:
    cap = spec.capacity or spec.n_tenants
    bank = ModelBank(capacity=cap, form=form, serve_form=serve_form)
    for name, m in models.items():
        bank.add(name, m.theta, m.phi_wk)
    return BankService(bank, max_batch_requests=spec.batch_requests)


def replay(service: BankService, stream: list[ScoreRequest], *,
           tol: float, max_results: int) -> dict:
    """Run the stream through the service in request batches; returns
    results + the serving numbers."""
    base = {k: counters.get(f"bank.{k}")
            for k in ("admit", "evict", "dispatch", "cache_hit",
                      "cache_miss", "h2d_bytes", "h2d_transfers")}
    results = []
    latencies = []
    n_events = 0
    t0 = time.perf_counter()
    for lo in range(0, len(stream), service.max_batch_requests):
        batch = stream[lo:lo + service.max_batch_requests]
        tb = time.perf_counter()
        results.extend(service.score(batch, tol=tol,
                                     max_results=max_results))
        latencies.append(time.perf_counter() - tb)
        n_events += sum(int(r.doc_ids.size) for r in batch)
    wall = time.perf_counter() - t0
    delta = {k: counters.get(f"bank.{k}") - v for k, v in base.items()}
    cacheable = delta["cache_hit"] + delta["cache_miss"]
    lat = np.asarray(latencies)
    return {
        "results": results,
        "n_requests": len(stream),
        "n_events": n_events,
        "wall_s": round(wall, 4),
        "events_per_sec": round(n_events / max(wall, 1e-9), 1),
        "latency_p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "latency_p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
        "dispatches": delta["dispatch"],
        "cache_hit_rate": (round(delta["cache_hit"] / cacheable, 4)
                          if cacheable else None),
        "residency_churn": {"admits": delta["admit"],
                            "evicts": delta["evict"]},
        "h2d": {"bytes": delta["h2d_bytes"],
                "transfers": delta["h2d_transfers"]},
    }


def sequential_control(models: dict[str, TenantModel],
                       stream: list[ScoreRequest], *, tol: float,
                       max_results: int) -> dict:
    """The pre-bank serving shape: one `top_suspicious` dispatch per
    request against that tenant's own tables (device-resident up
    front, so the comparison isolates the dispatch collapse — the
    sequential loop's per-tenant H2D staging is charged separately in
    the artifact's h2d counters). Winners are the parity oracle."""
    import jax.numpy as jnp

    from onix.models.scoring import top_suspicious

    dev = {name: (jnp.asarray(m.theta), jnp.asarray(m.phi_wk))
           for name, m in models.items()}
    results = []
    n_events = 0
    t0 = time.perf_counter()
    for req in stream:
        th, ph = dev[req.tenant]
        n = int(req.doc_ids.size)
        res = top_suspicious(th, ph, jnp.asarray(req.doc_ids),
                             jnp.asarray(req.word_ids),
                             jnp.ones(n, jnp.float32), tol=tol,
                             max_results=max_results)
        results.append((np.asarray(res.scores), np.asarray(res.indices)))
        n_events += n
    wall = time.perf_counter() - t0
    return {
        "results": results,
        "n_events": n_events,
        "wall_s": round(wall, 4),
        "events_per_sec": round(n_events / max(wall, 1e-9), 1),
        "dispatches": len(stream),
    }


def assert_parity(banked, sequential) -> None:
    """Bit-identical winners between the banked replay and the
    sequential oracle — scores AND indices, every request (cached
    results included: the cache stores exactly what the bank scored)."""
    for i, (b, (s_ref, i_ref)) in enumerate(
            zip(banked["results"], sequential["results"])):
        if not (np.array_equal(b.topk.scores, s_ref)
                and np.array_equal(b.topk.indices, i_ref)):
            raise AssertionError(
                f"request {i}: banked winners diverged from the "
                f"single-tenant path")


def assert_residency_identity(capped, uncapped) -> None:
    """A capacity-capped replay's winners are identical to the uncapped
    run's — the LRU proof (eviction on request boundaries only)."""
    for i, (a, b) in enumerate(zip(capped["results"],
                                   uncapped["results"])):
        if not (np.array_equal(a.topk.scores, b.topk.scores)
                and np.array_equal(a.topk.indices, b.topk.indices)):
            raise AssertionError(
                f"request {i}: capped-bank winners diverged from the "
                f"uncapped run")


def run_harness(spec: HarnessSpec, form: str = "auto",
                with_sequential: bool = True,
                with_uncapped_check: bool = True) -> dict:
    """One full harness pass: replay + parity + (optionally) the
    capped-vs-uncapped residency proof. Returns the artifact dict
    (results stripped)."""
    models = make_tenants(spec)
    stream = make_stream(spec)
    service = build_service(spec, models, form=form)
    # Warm pass compiles every program shape (serving runs warm; cold
    # compile is a one-time cost) — on a FRESH service so the timed
    # replay still exercises admission/caching from empty.
    warm = build_service(spec, models, form=form)
    replay(warm, stream, tol=spec.tol, max_results=spec.max_results)
    banked = replay(service, stream, tol=spec.tol,
                    max_results=spec.max_results)
    out = {"spec": dataclasses.asdict(spec), "form": form,
           "banked": {k: v for k, v in banked.items() if k != "results"}}
    if with_sequential:
        seq = sequential_control(models, stream, tol=spec.tol,
                                 max_results=spec.max_results)
        assert_parity(banked, seq)
        out["sequential"] = {k: v for k, v in seq.items()
                            if k != "results"}
        out["parity_bit_identical"] = True
        out["speedup_banked_vs_sequential"] = round(
            banked["events_per_sec"] / max(seq["events_per_sec"], 1e-9), 3)
    if with_uncapped_check and spec.capacity \
            and spec.capacity < spec.n_tenants:
        unspec = dataclasses.replace(spec, capacity=0)
        uncapped = replay(build_service(unspec, models, form=form), stream,
                          tol=spec.tol, max_results=spec.max_results)
        assert_residency_identity(banked, uncapped)
        out["capped_winners_identical_to_uncapped"] = True
        assert banked["residency_churn"]["evicts"] > 0, (
            "capped replay evicted nothing — the residency proof was "
            "vacuous; shrink capacity or skew the stream harder")
    return out
