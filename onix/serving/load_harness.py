"""Mixed-tenant load harness for the model bank (r12) + the r16
serving-resilience SLO cells.

Replays a skewed (Zipf) tenant traffic stream through `BankService`
and reports the serving numbers the bank is judged on: aggregate
events/s, per-OUTCOME latency histograms (served / degraded / shed /
deadline-expired / refused, p50/p99 each — the r16 SLO accounting),
winner-cache hit rate, and residency churn (admits/evicts) — plus the
proofs:

* **parity** — every scored request's bottom-M winners bit-identical
  to the single-tenant `top_suspicious` path run per request;
* **residency identity** — a capacity-capped replay produces winners
  identical to an uncapped replay of the same stream (eviction happens
  only at request-batch boundaries, so it can never change a score);
* **overload cell** (`overload_cell`) — at ≥2× sustainable offered
  load the service SHEDS (503-semantics `Overloaded`) while the
  served-request p99 stays within `p99_bound_factor`× the uncontended
  p99, and shed requests provably leave bank residency and the winner
  cache untouched (docs/ROBUSTNESS.md "serving resilience").

`scripts/exp_model_bank.py` is the CLI wrapper that adds interleaved
sequential-vs-banked timing arms and writes the measured artifact
(docs/BANK_r12_cpu.json); tests/test_model_bank_smoke.py and
tests/test_serve_resilience.py run this harness at tiny shapes in
tier-1 so it cannot rot between TPU tunnel windows (the
test_fit_gap_smoke discipline).
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from onix.serving.model_bank import (BankRefusal, BankService, ModelBank,
                                     ScoreRequest, TenantModel)
from onix.utils import telemetry
from onix.utils.obs import counters
from onix.utils.resilience import DeadlineExceeded, Overloaded


@dataclasses.dataclass
class HarnessSpec:
    """Shape of one harness run. Defaults are the acceptance shape
    (64 resident tenants); the tier-1 smoke shrinks everything."""
    n_tenants: int = 64
    n_docs: int = 2048          # per-tenant document count (D)
    n_vocab: int = 1024         # per-tenant product-vocabulary size (V)
    n_topics: int = 20
    n_requests: int = 256       # total requests in the replay stream
    events_per_request: int = 2048
    n_windows: int = 4          # windows per tenant; repeats -> cache hits
    #                             (0 = uncached stream: every request a
    #                             fresh window=None event set — the pure
    #                             scoring-throughput arm)
    zipf_a: float = 1.2         # tenant popularity skew
    batch_requests: int = 64    # service batching (requests per score())
    capacity: int = 0           # resident cap; 0 = all tenants resident
    tol: float = 1.0
    max_results: int = 100
    seed: int = 0
    # r16 admission control (serving.max_queue_depth /
    # serving.request_deadline_ms equivalents): 0 = disabled, the
    # pre-r16 shape. The overload cell sets max_queue_depth=1 so the
    # served-latency bound (depth+1)·service-time is provable.
    max_queue_depth: int = 0
    request_deadline_ms: float = 0.0
    # r20 scale-out: `devices` > 1 builds the bank over that many mesh
    # devices (jax.devices()[:n] — virtual on CPU) with `shard_form`
    # routed through select_shard_form; `replicas` > 1 stands up N
    # services behind a ReplicaFront; `prefetch_depth`/`host_capacity`
    # exercise the host-RAM residency tier.
    devices: int = 0
    shard_form: str = "auto"
    replicas: int = 1
    prefetch_depth: int = 0
    host_capacity: int = 0


def make_tenants(spec: HarnessSpec) -> dict[str, TenantModel]:
    """Synthetic per-tenant (θ, φ) tables — Dirichlet rows, one shared
    shape class (the common case: tenants of one datatype × day ladder
    into the same pow2 bucket)."""
    rng = np.random.default_rng(spec.seed)
    out = {}
    for t in range(spec.n_tenants):
        theta = rng.dirichlet(np.full(spec.n_topics, 0.5),
                              size=spec.n_docs).astype(np.float32)
        phi = rng.dirichlet(np.full(spec.n_topics, 0.5),
                            size=spec.n_vocab).astype(np.float32)
        out[f"t{t:04d}"] = TenantModel(theta, phi)
    return out


def make_stream(spec: HarnessSpec) -> list[ScoreRequest]:
    """Zipf-skewed request stream. Each (tenant, window) pair's event
    set is generated ONCE and reused on every repeat — the winner
    cache's immutable-window contract, and what real replay traffic
    (dashboards re-opening a scored day) looks like."""
    rng = np.random.default_rng(spec.seed + 1)
    ranks = (rng.zipf(spec.zipf_a, spec.n_requests) - 1) % spec.n_tenants
    # Scatter ranks so hot tenants aren't id-contiguous (same trick as
    # bench._zipf_pairs).
    tenant_ids = (ranks * 2654435761) % spec.n_tenants
    events: dict[tuple[int, int], tuple[np.ndarray, np.ndarray]] = {}
    stream = []

    def draw(n):
        return (rng.integers(0, spec.n_docs, n).astype(np.int32),
                rng.integers(0, spec.n_vocab, n).astype(np.int32))

    for t in tenant_ids:
        if spec.n_windows:
            w = int(rng.integers(spec.n_windows))
            key = (int(t), w)
            if key not in events:
                events[key] = draw(spec.events_per_request)
            d, wd = events[key]
            window = f"w{w}"
        else:
            d, wd = draw(spec.events_per_request)
            window = None
        stream.append(ScoreRequest(tenant=f"t{int(t):04d}", doc_ids=d,
                                   word_ids=wd, window=window))
    return stream


def build_service(spec: HarnessSpec, models: dict[str, TenantModel],
                  form: str = "auto", serve_form: str = "auto"):
    """One service (the pre-r20 shape), or the r20 scale-out fabric
    when the spec asks for it: a mesh-sharded bank (spec.devices > 1),
    the host-RAM tier (host_capacity / prefetch_depth — tenants arrive
    loader-backed so the tier actually churns), and/or N replicas
    behind a ReplicaFront (spec.replicas > 1)."""
    cap = spec.capacity or spec.n_tenants
    devices = None
    if spec.devices:
        import jax
        if spec.devices > len(jax.devices()):
            raise ValueError(
                f"spec.devices={spec.devices} > available "
                f"{len(jax.devices())} (set "
                "xla_force_host_platform_device_count)")
        devices = jax.devices()[:spec.devices]
    tiered = bool(spec.host_capacity or spec.prefetch_depth)

    def bulk_loader(names: list[str]) -> dict[str, TenantModel]:
        return {n: models[n] for n in names if n in models}

    def _one():
        bank = ModelBank(
            capacity=cap, form=form, serve_form=serve_form,
            devices=devices, shard_form=spec.shard_form,
            prefetch_depth=spec.prefetch_depth,
            host_capacity=spec.host_capacity,
            loader=(lambda t: models.get(t)) if tiered else None,
            bulk_loader=bulk_loader if tiered else None)
        if not tiered:
            # Pre-r20 shape: everything explicitly add()ed (pinned in
            # the host registry). The tiered path leaves tenants to
            # the loader so promote/demote across host RAM is real.
            for name, m in models.items():
                bank.add(name, m.theta, m.phi_wk)
        return BankService(bank,
                           max_batch_requests=spec.batch_requests,
                           max_queue_depth=spec.max_queue_depth,
                           request_deadline_s=(
                               spec.request_deadline_ms / 1e3))

    if spec.replicas > 1:
        from onix.serving.replicas import ReplicaFront
        return ReplicaFront([_one() for _ in range(spec.replicas)])
    return _one()


def _pctl(latencies: list[float]) -> dict:
    """Quantiles via the r18 log-bucketed `telemetry.Histogram` — the
    same machinery `/metrics` exposes, replacing the pre-r18 raw
    index-into-sorted-list math whose p99 on small n was whatever
    single sample the truncation landed on. The histogram's answer is
    exact-to-the-bucket with a declared relative error bound
    (`q_rel_error`), and parity against numpy nearest-rank percentile
    is asserted in tests/test_telemetry.py."""
    h = telemetry.Histogram()
    for v in latencies:
        h.observe(v)
    return {"n": len(latencies),
            "p50_ms": round(h.quantile(0.50) * 1e3, 3),
            "p99_ms": round(h.quantile(0.99) * 1e3, 3),
            "q_rel_error": round(h.rel_error, 4)}


def _slo(outcomes: dict[str, list[float]]) -> dict:
    """Per-outcome latency histograms — the r16 SLO accounting. Every
    request batch lands in exactly one outcome class: served (scored,
    current-epoch winners), degraded (served with the explicit
    overload/fallback stamp), shed (admission refusal — 503), deadline
    (budget expired in queue — 503), refused (BankRefusal — 404).
    Latency is recorded for ALL classes: a shed request's latency IS
    the shed path's cost, and it staying microseconds-flat under
    overload is the admission-control claim."""
    return {k: _pctl(v) for k, v in outcomes.items() if v}


def replay(service: BankService, stream: list[ScoreRequest], *,
           tol: float, max_results: int, shed_retries: int = 0,
           shed_backoff_s: float = 0.0, keep_raw: bool = False) -> dict:
    """Run the stream through the service in request batches via the
    admission-controlled submit() path; returns results + the serving
    numbers. A shed/deadline-refused batch is retried up to
    `shed_retries` times (honoring `shed_backoff_s` between tries —
    the harness's stand-in for a client honoring Retry-After), then
    recorded under its outcome with None results — parity asserts skip
    those slots. Each batch lands in exactly ONE outcome class (its
    FINAL attempt's — so `slo.*.n` sums to the batch count and
    reconciles with the admission deltas); retried attempts are
    tallied separately under `shed_attempts_retried`."""
    base = {k: counters.get(f"bank.{k}")
            for k in ("admit", "evict", "dispatch", "cache_hit",
                      "cache_miss", "h2d_bytes", "h2d_transfers",
                      "tier_hbm_hit", "tier_host_hit", "tier_disk_load",
                      "prefetch_promoted", "prefetch_hit",
                      "prefetch_waste", "prefetch_failed",
                      "fetch_wait_us")}
    # Serve-tier counters are process-global and cumulative; a replay's
    # artifact must report ITS OWN deltas (the bank-counter discipline
    # above) — warm passes and earlier arms in the same process would
    # otherwise inflate every later replay's admission numbers.
    serve_keys = ("shed", "shed_requests", "deadline_expired",
                  "degraded", "form_fallback", "served")
    serve_base = {k: counters.get(f"serve.{k}") for k in serve_keys}
    results: list = []
    outcomes: dict[str, list[float]] = {
        "served": [], "degraded": [], "shed": [], "deadline": [],
        "refused": []}
    # r20 per-tier latency: each SCORED batch classifies by the worst
    # residency tier it touched (disk > host RAM > HBM, read off the
    # per-batch bank.tier_* counter deltas) — "a request that had to
    # go to disk cost THIS much" is the number the tier exists to
    # improve, and the artifact's per-tier p50/p99 comes from here.
    _tier_keys = ("tier_disk_load", "tier_host_hit", "tier_hbm_hit")
    tier_lats: dict[str, list[float]] = {
        "hbm": [], "host": [], "disk": []}
    n_events = 0
    retried = 0
    t0 = time.perf_counter()
    for lo in range(0, len(stream), service.max_batch_requests):
        batch = stream[lo:lo + service.max_batch_requests]
        tier_base = {k: counters.get(f"bank.{k}") for k in _tier_keys}
        out, kind, lat = None, "shed", 0.0
        for attempt in range(shed_retries + 1):
            tb = time.perf_counter()
            try:
                out = service.submit(batch, tol=tol,
                                     max_results=max_results)
                kind = ("degraded" if any(r.degraded for r in out)
                        else "served")
            except Overloaded:
                kind = "shed"
            except DeadlineExceeded:
                kind = "deadline"
            except BankRefusal:
                kind = "refused"
            lat = time.perf_counter() - tb
            if out is not None or attempt == shed_retries \
                    or kind == "refused":
                break
            retried += 1
            if shed_backoff_s:
                time.sleep(shed_backoff_s)
        outcomes[kind].append(lat)        # final outcome only
        results.extend(out if out is not None else [None] * len(batch))
        if out is not None:
            n_events += sum(int(r.doc_ids.size) for r in batch)
            td = {k: counters.get(f"bank.{k}") - tier_base[k]
                  for k in _tier_keys}
            tier = ("disk" if td["tier_disk_load"] else
                    "host" if td["tier_host_hit"] else "hbm")
            tier_lats[tier].append(lat)
    wall = time.perf_counter() - t0
    delta = {k: counters.get(f"bank.{k}") - v for k, v in base.items()}
    cacheable = delta["cache_hit"] + delta["cache_miss"]
    scored = _pctl(outcomes["served"] + outcomes["degraded"] or [0.0])
    admission = {k: counters.get(f"serve.{k}") - serve_base[k]
                 for k in serve_keys}
    admission["shed_attempts_retried"] = retried
    admission["max_queue_depth"] = service.max_queue_depth
    admission["queue_depth_peak"] = service.peak_depth
    return {
        "results": results,
        "n_requests": len(stream),
        "n_events": n_events,
        "wall_s": round(wall, 4),
        "events_per_sec": round(n_events / max(wall, 1e-9), 1),
        "latency_p50_ms": scored["p50_ms"],
        "latency_p99_ms": scored["p99_ms"],
        "slo": _slo(outcomes),
        # Raw per-batch latencies, on request only (the histogram-vs-
        # numpy parity test; artifacts carry the histograms instead).
        **({"raw_latencies": {k: list(v) for k, v in outcomes.items()}}
           if keep_raw else {}),
        "admission": admission,
        "dispatches": delta["dispatch"],
        "cache_hit_rate": (round(delta["cache_hit"] / cacheable, 4)
                          if cacheable else None),
        "residency_churn": {"admits": delta["admit"],
                            "evicts": delta["evict"]},
        "h2d": {"bytes": delta["h2d_bytes"],
                "transfers": delta["h2d_transfers"]},
        # r20: per-tier latency + tier/prefetch accounting (deltas, the
        # same discipline as the bank counters above). `wave_dispatches`
        # is per-home-device (bank.wave.d<i>) — process-cumulative, so
        # it appears only when the sharded path ran at all.
        "tier_latency": {t: _pctl(v) for t, v in tier_lats.items()
                         if v},
        "tiers": {"hbm_hits": delta["tier_hbm_hit"],
                  "host_hits": delta["tier_host_hit"],
                  "disk_loads": delta["tier_disk_load"]},
        "prefetch": {
            "promoted": delta["prefetch_promoted"],
            "hits": delta["prefetch_hit"],
            "waste": delta["prefetch_waste"],
            "failed": delta["prefetch_failed"],
            "hit_rate": (round(delta["prefetch_hit"]
                               / delta["prefetch_promoted"], 4)
                         if delta["prefetch_promoted"] else None)},
        "fetch_wait_us": delta["fetch_wait_us"],
        "wave_dispatches": {
            k.split("bank.wave.", 1)[1]: v
            for k, v in counters.snapshot("bank").items()
            if k.startswith("bank.wave.d")},
    }


def sequential_control(models: dict[str, TenantModel],
                       stream: list[ScoreRequest], *, tol: float,
                       max_results: int) -> dict:
    """The pre-bank serving shape: one `top_suspicious` dispatch per
    request against that tenant's own tables (device-resident up
    front, so the comparison isolates the dispatch collapse — the
    sequential loop's per-tenant H2D staging is charged separately in
    the artifact's h2d counters). Winners are the parity oracle."""
    import jax.numpy as jnp

    from onix.models.scoring import top_suspicious

    dev = {name: (jnp.asarray(m.theta), jnp.asarray(m.phi_wk))
           for name, m in models.items()}
    results = []
    n_events = 0
    t0 = time.perf_counter()
    for req in stream:
        th, ph = dev[req.tenant]
        n = int(req.doc_ids.size)
        res = top_suspicious(th, ph, jnp.asarray(req.doc_ids),
                             jnp.asarray(req.word_ids),
                             jnp.ones(n, jnp.float32), tol=tol,
                             max_results=max_results)
        results.append((np.asarray(res.scores), np.asarray(res.indices)))
        n_events += n
    wall = time.perf_counter() - t0
    return {
        "results": results,
        "n_events": n_events,
        "wall_s": round(wall, 4),
        "events_per_sec": round(n_events / max(wall, 1e-9), 1),
        "dispatches": len(stream),
    }


def assert_parity(banked, sequential) -> None:
    """Bit-identical winners between the banked replay and the
    sequential oracle — scores AND indices, every request (cached
    results included: the cache stores exactly what the bank scored)."""
    for i, (b, (s_ref, i_ref)) in enumerate(
            zip(banked["results"], sequential["results"])):
        if b is None:
            raise AssertionError(
                f"request {i}: not served (shed/refused) — parity is "
                "undefined; run parity replays without admission limits")
        if not (np.array_equal(b.topk.scores, s_ref)
                and np.array_equal(b.topk.indices, i_ref)):
            raise AssertionError(
                f"request {i}: banked winners diverged from the "
                f"single-tenant path")


def assert_residency_identity(capped, uncapped) -> None:
    """A capacity-capped replay's winners are identical to the uncapped
    run's — the LRU proof (eviction on request boundaries only)."""
    for i, (a, b) in enumerate(zip(capped["results"],
                                   uncapped["results"])):
        if not (np.array_equal(a.topk.scores, b.topk.scores)
                and np.array_equal(a.topk.indices, b.topk.indices)):
            raise AssertionError(
                f"request {i}: capped-bank winners diverged from the "
                f"uncapped run")


def overload_cell(spec: HarnessSpec, *, n_producers: int = 4,
                  duration_s: float = 0.0,
                  p99_bound_factor: float = 2.0,
                  min_offered_factor: float = 2.0,
                  n_probes: int = 8, form: str = "auto") -> dict:
    """The r16 overload proof (ISSUE 12 acceptance; docs/ROBUSTNESS.md
    "serving resilience"): drive the service at >= `min_offered_factor`
    × its sustainable throughput and prove it DEGRADES PREDICTABLY —
    requests shed (503-semantics `Overloaded`) while the served-request
    p99 stays within `p99_bound_factor`× the uncontended p99 — instead
    of collapsing into an unbounded queue.

    Three phases, all asserted in-cell:

    1. **uncontended** — closed-loop passes over the stream on an
       unbounded service: pass 0 absorbs compiles + admissions, the
       later passes pool their per-batch latencies into the
       uncontended p50/p99 denominator (pooled across passes — a
       single pass's p99 is one scheduler hiccup wide) and the
       sustainable batches/s rate.
    2. **overload** — `n_producers` TIME-BOXED producers over a fresh
       pre-warmed service with `max_queue_depth=1`: exactly one batch
       in flight, zero queued, so a served request's latency is pure
       service time — no queue wait can inflate the tail, which is
       what makes the p99 bound structural rather than lucky.
       Everything that arrives while a batch is in flight SHEDS.
       Producers nap one median batch wall after a shed (the harness
       stand-in for honoring Retry-After) so offered load is a
       measured arrival rate, not a spin loop — each napper still
       arrives ~once per service time, so n producers offer ~n× the
       sustainable rate. Asserts: shed > 0, offered factor >=
       `min_offered_factor`, served p99 <= `p99_bound_factor` × the
       CALIBRATED denominator: max(uncontended p99, served p50 ×
       uncontended p99/p50). The second arm keys the bound to the
       host conditions measured DURING the overload run — a saturated
       host shifts the whole served distribution and the bound with
       it, while an unbounded queue (tail inflating relative to the
       served median) still fails.
    3. **shed probe** — with the scoring lock held (an in-flight batch)
       and the queue slot taken by a real blocked submit, `n_probes`
       windowed requests are fired and must ALL shed; bank residency
       (per-shard LRU order), the winner-cache keys, and the
       admit/evict counters are asserted byte-identical across the
       probes — shed requests provably mutate NOTHING.

    The overload stream is the spec's stream with windows stripped
    (window=None) so every batch scores — uniform batch cost is what
    makes the 2× bound tight rather than cache-hit noise."""
    models = make_tenants(spec)
    stream = make_stream(spec)
    nocache = [dataclasses.replace(r, window=None) for r in stream]
    n_batches = max(1, -(-len(stream) // spec.batch_requests))

    # -- phase 1: sustainable rate + uncontended p99 ---------------------
    base_spec = dataclasses.replace(spec, max_queue_depth=0,
                                    request_deadline_ms=0.0)
    unc_svc = build_service(base_spec, models, form=form)
    nocache_batches = [nocache[lo:lo + spec.batch_requests]
                       for lo in range(0, len(nocache),
                                       spec.batch_requests)]
    lat_by_pass: list[list[float]] = []
    for _ in range(3):
        lats = []
        for batch in nocache_batches:
            tb = time.perf_counter()
            unc_svc.submit(batch, tol=spec.tol,
                           max_results=spec.max_results)
            lats.append(time.perf_counter() - tb)
        lat_by_pass.append(lats)
    pooled = np.asarray([v for lats in lat_by_pass[1:] for v in lats])
    unc_p99_s = float(np.percentile(pooled, 99))
    unc_p50_s = float(np.percentile(pooled, 50))
    unc_wall_s = float(sum(lat_by_pass[-1]))
    sustainable_batches_per_s = n_batches / unc_wall_s

    # -- phase 2: overload ----------------------------------------------
    over_spec = dataclasses.replace(spec, max_queue_depth=1,
                                    request_deadline_ms=0.0)
    svc = build_service(over_spec, models, form=form)
    # Warm pass (single-threaded, never sheds at depth 1): residency +
    # compiles settle so overload batch walls are steady-state.
    replay(svc, nocache, tol=spec.tol, max_results=spec.max_results)
    duration_s = duration_s or max(0.5, 3.0 * unc_wall_s)
    # A full-batch nap after a shed: the napper wakes ~once per service
    # time (offered still n_producers x sustainable) without peppering
    # the scorer's cores with sub-ms wakeups — scheduler noise on a
    # small host would otherwise inflate the served tail with producer
    # wakeup costs the service never caused.
    shed_nap_s = max(unc_p50_s, 1e-4)
    out_lock = threading.Lock()
    lat_served: list[float] = []
    tally = {"served": 0, "degraded": 0, "shed": 0, "attempts": 0}
    batches = [stream[lo:lo + spec.batch_requests]
               for lo in range(0, len(stream), spec.batch_requests)]
    stop_t = [0.0]     # set after the threads are built, read by all

    # Pre-stripped batches: producers must not burn GIL time building
    # request objects inside the timed loop — that would inflate the
    # SERVED latencies with producer-side work the service never sees.
    stripped = [[dataclasses.replace(r, window=None) for r in b]
                for b in batches]

    def producer(pid: int) -> None:
        i = 0
        while time.perf_counter() < stop_t[0]:
            batch = stripped[(pid + i) % len(stripped)]
            i += 1
            tb = time.perf_counter()
            try:
                res = svc.submit(batch, tol=spec.tol,
                                 max_results=spec.max_results)
                lat = time.perf_counter() - tb
                with out_lock:
                    tally["attempts"] += 1
                    lat_served.append(lat)
                    tally["degraded" if any(r.degraded for r in res)
                          else "served"] += 1
            except Overloaded:
                with out_lock:
                    tally["attempts"] += 1
                    tally["shed"] += 1
                time.sleep(shed_nap_s)

    threads = [threading.Thread(target=producer, args=(p,))
               for p in range(n_producers)]
    t0 = time.perf_counter()
    stop_t[0] = t0 + duration_s
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    over_wall = time.perf_counter() - t0
    offered_batches_per_s = tally["attempts"] / over_wall
    offered_factor = offered_batches_per_s / sustainable_batches_per_s
    lat_arr = np.asarray(lat_served)
    served_p99_s = float(np.percentile(lat_arr, 99)) \
        if lat_served else float("inf")
    served_p50_s = float(np.percentile(lat_arr, 50)) \
        if lat_served else float("inf")

    # In-run calibration of the p99 bound. The uncontended phase ran on
    # whatever host quiet happened to hold THEN; the overload phase adds
    # n_producers runnable threads, and on a saturated host (tier-1
    # suites sharing cores) every served batch — median included — pays
    # scheduler contention the uncontended denominator never saw. A
    # fixed `factor × unc_p99` bound then flakes on slowness the
    # SERVICE didn't cause. The served p50 measures that contention
    # in-run: scale the uncontended tail RATIO (p99/p50, the shape of a
    # healthy latency distribution) up to the served median and take
    # the looser of the two denominators. An unbounded queue still
    # fails — queue wait inflates the tail relative to the served
    # median, not uniformly — while uniform host slowness passes.
    unc_tail_ratio = unc_p99_s / max(unc_p50_s, 1e-9)
    calibrated_floor = served_p50_s * unc_tail_ratio
    p99_bound_s = p99_bound_factor * max(unc_p99_s, calibrated_floor)

    assert tally["shed"] > 0, (
        "overload cell shed nothing — offered load never exceeded the "
        "queue; raise n_producers or shrink the batch")
    assert tally["served"] + tally["degraded"] > 0, \
        "overload cell served nothing — the service wedged"
    assert offered_factor >= min_offered_factor, (
        f"offered load {offered_factor:.2f}x sustainable — below the "
        f"{min_offered_factor}x overload bar (producers too slow)")
    assert served_p99_s <= p99_bound_s, (
        f"served p99 {served_p99_s * 1e3:.1f}ms exceeded the calibrated "
        f"bound {p99_bound_s * 1e3:.1f}ms ({p99_bound_factor}x "
        f"max(uncontended p99 {unc_p99_s * 1e3:.1f}ms, served p50 "
        f"{served_p50_s * 1e3:.1f}ms x tail ratio "
        f"{unc_tail_ratio:.2f})) — admission failed to bound latency")

    # -- phase 3: shed probe (shed mutates NOTHING) ----------------------
    def residency_snapshot():
        return {k: list(sh.lru) for k, sh in svc.bank._shards.items()}

    before = {"cache": set(svc._cache), "lru": residency_snapshot(),
              "admit": counters.get("bank.admit"),
              "evict": counters.get("bank.evict"),
              "cache_epoch_evictions":
                  counters.get("bank.cache_epoch_evictions")}
    errs: list[BaseException] = []

    def blocked_submit():
        try:
            svc.submit(batches[0], tol=spec.tol,
                       max_results=spec.max_results)
        except BaseException as e:  # surfaced to the cell, never lost
            counters.inc("serve.harness_blocked_submit_error")
            errs.append(e)

    probes_shed = 0
    with svc.lock:      # an in-flight batch holds the scorer...
        blockers = [threading.Thread(target=blocked_submit)]
        for b in blockers:
            b.start()   # ...and the depth-1 slot fills with a real waiter
        deadline = time.perf_counter() + 10.0
        while svc.admission_stats()["queue_depth"] < 1:
            if time.perf_counter() > deadline:
                raise AssertionError("queue slot never filled")
            time.sleep(0.001)
        for p in range(n_probes):
            probe = ScoreRequest(tenant=batches[0][0].tenant,
                                 doc_ids=batches[0][0].doc_ids,
                                 word_ids=batches[0][0].word_ids,
                                 window=f"probe{p}")
            try:
                svc.submit([probe], tol=spec.tol,
                           max_results=spec.max_results)
            except Overloaded as e:
                probes_shed += 1
                assert e.retry_after_s > 0
        # Asserted while the lock is still held — the blocked waiters
        # have not scored, so any mutation here came from a probe.
        assert probes_shed == n_probes, \
            f"{n_probes - probes_shed} probes were admitted past a " \
            "full queue"
        assert set(svc._cache) == before["cache"], \
            "a shed request touched the winner cache"
        assert residency_snapshot() == before["lru"], \
            "a shed request perturbed bank residency"
        for c in ("admit", "evict", "cache_epoch_evictions"):
            assert counters.get(f"bank.{c}") == before[c], \
                f"a shed request moved bank.{c}"
    for b in blockers:
        b.join(timeout=30)
    assert not errs, f"blocked submits failed: {errs!r}"

    return {
        "spec": dataclasses.asdict(spec), "form": form,
        "uncontended": {"wall_s": round(unc_wall_s, 4),
                        "p50_ms": round(unc_p50_s * 1e3, 3),
                        "p99_ms": round(unc_p99_s * 1e3, 3),
                        "sustainable_batches_per_s":
                            round(sustainable_batches_per_s, 2)},
        "overload": {
            "n_producers": n_producers,
            "duration_s": round(duration_s, 3),
            "attempts": tally["attempts"],
            "wall_s": round(over_wall, 4),
            "offered_batches_per_s": round(offered_batches_per_s, 2),
            "offered_factor_vs_sustainable": round(offered_factor, 2),
            "outcomes": dict(tally),
            "served_p50_ms": round(served_p50_s * 1e3, 3),
            "served_p99_ms": round(served_p99_s * 1e3, 3),
            "served_p99_vs_uncontended":
                round(served_p99_s / max(unc_p99_s, 1e-9), 3),
            "p99_bound_factor": p99_bound_factor,
            # Calibration evidence: which denominator the bound used
            # (uncontended p99, or the served-median-scaled tail floor
            # on a saturated host) and the resulting absolute bound.
            "unc_tail_ratio": round(unc_tail_ratio, 3),
            "p99_bound_ms": round(p99_bound_s * 1e3, 3),
            "p99_bound_calibrated": bool(calibrated_floor > unc_p99_s),
        },
        "shed_probe": {"probes": n_probes, "shed": probes_shed,
                       "state_untouched": True},
        "p99_bounded_while_shedding": True,
    }


def run_harness(spec: HarnessSpec, form: str = "auto",
                with_sequential: bool = True,
                with_uncapped_check: bool = True) -> dict:
    """One full harness pass: replay + parity + (optionally) the
    capped-vs-uncapped residency proof. Returns the artifact dict
    (results stripped)."""
    models = make_tenants(spec)
    stream = make_stream(spec)
    service = build_service(spec, models, form=form)
    # Warm pass compiles every program shape (serving runs warm; cold
    # compile is a one-time cost) — on a FRESH service so the timed
    # replay still exercises admission/caching from empty.
    warm = build_service(spec, models, form=form)
    replay(warm, stream, tol=spec.tol, max_results=spec.max_results)
    banked = replay(service, stream, tol=spec.tol,
                    max_results=spec.max_results)
    out = {"spec": dataclasses.asdict(spec), "form": form,
           "banked": {k: v for k, v in banked.items() if k != "results"}}
    if with_sequential:
        seq = sequential_control(models, stream, tol=spec.tol,
                                 max_results=spec.max_results)
        assert_parity(banked, seq)
        out["sequential"] = {k: v for k, v in seq.items()
                            if k != "results"}
        out["parity_bit_identical"] = True
        out["speedup_banked_vs_sequential"] = round(
            banked["events_per_sec"] / max(seq["events_per_sec"], 1e-9), 3)
    if with_uncapped_check and spec.capacity \
            and spec.capacity < spec.n_tenants:
        unspec = dataclasses.replace(spec, capacity=0)
        uncapped = replay(build_service(unspec, models, form=form), stream,
                          tol=spec.tol, max_results=spec.max_results)
        assert_residency_identity(banked, uncapped)
        out["capped_winners_identical_to_uncapped"] = True
        assert banked["residency_churn"]["evicts"] > 0, (
            "capped replay evicted nothing — the residency proof was "
            "vacuous; shrink capacity or skew the stream harder")
    return out
