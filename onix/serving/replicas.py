"""Multi-replica serving front (r20): N `BankService` replicas behind
one routing fabric, with the epoch-propagation contract the ROADMAP
names as the hard correctness piece of scale-out.

Routing is the same collective-free placement argument as the in-bank
device sharding one level down (model_bank.select_shard_form): a
tenant's HOME replica is `crc32(tenant) % n`, walked forward past
replicas marked down, so every request for a tenant lands on one
replica and its winner cache / residency never needs cross-replica
coordination. The hash is coordination-free — any front process over
the same replica list computes the same placement.

**Epoch propagation.** Out-of-band epoch bumps reach a tenant's next
score through two independent paths, either of which alone upholds the
"no replica serves pre-bump winners after the bump is durable"
contract:

1. *Disk re-saves* (daily refit, online nudge by another process):
   every replica's `BankService._score_locked` already probes
   `refresh_from_disk` per distinct tenant per call (r13) — a durable
   re-save moves the epoch before any cached winner can hit, on
   whichever replica the request lands.
2. *In-process feedback installs* (`POST /feedback`): the front keeps
   an **epoch bulletin** — a monotonically-sequenced log of
   (base, filter) installs. `publish_feedback` records the entry and
   eagerly applies it to every live replica; `submit` additionally
   replays any entries a target replica has not yet applied
   (`_sync_epochs`) BEFORE dispatching its wave. The eager path makes
   the common case immediate; the pre-dispatch replay makes the
   contract structural — a replica that missed the eager install
   (marked down and later routed to on failover, a racing publish)
   still applies the bump before it can score the tenant.

**Failover.** A replica raising `ReplicaDown` mid-batch is marked
down and its wave re-routes to the surviving replicas
(`serve.replica_failover`); re-routed tenants sync the bulletin on
their new home first, so failover never reintroduces pre-bump
winners. Winners are unchanged by construction: every replica scores
from the same model store through the same `_scan_bottom_k` kernels,
so WHICH replica answers never changes WHAT it answers — asserted by
the chaos cell in tests/test_replicas.py.

The front duck-types the `BankService` surface the serve layer and
load harness use (`submit`, `apply_feedback_filter`,
`admission_stats`, `cache_stats`, `max_batch_requests`, `lock`), so
`oa/serve.py` and `load_harness.replay` drive either transparently.
"""

from __future__ import annotations

import threading
import zlib

from onix.utils.obs import counters


class ReplicaDown(RuntimeError):
    """A replica is gone (process death, connection torn down). The
    front absorbs it by re-routing; it surfaces only when no replica
    remains alive."""


class ReplicaFront:
    """Route request batches across N `BankService` replicas with the
    epoch-bulletin propagation contract (module docstring)."""

    #: Lock discipline (r17 `locks` pass): the bulletin log, per-replica
    #: applied cursors, and liveness set are shared across handler
    #: threads and mutate only under `lock`. Ordering is front.lock ->
    #: replica.lock everywhere (publish and sync both), so the two
    #: tiers can never deadlock.
    GUARDED_BY = {"_bulletin": "lock",
                  "_applied": "lock",
                  "_down": "lock",
                  "_seq": "lock"}

    def __init__(self, services: list):
        if not services:
            raise ValueError("ReplicaFront needs >= 1 replica service")
        self.replicas = list(services)
        # RLock: oa/serve.py's /feedback handler wraps the install in
        # `with service.lock:` before calling apply_feedback_filter —
        # which re-enters here.
        self.lock = threading.RLock()
        self._down: set[int] = set()
        # Epoch bulletin: base -> (seq, filt). One entry per base — a
        # newer install supersedes the older one wholesale (the filter
        # compiled from the CSV contains every preceding append, the
        # same last-installer-wins argument as oa/serve.py's /feedback).
        self._bulletin: dict[str, tuple[int, object]] = {}
        self._seq = 0
        # Per-(replica, base) applied cursor: seq of the newest
        # bulletin entry this replica has installed.
        self._applied: dict[tuple[int, str], int] = {}

    # -- placement --------------------------------------------------------

    def n_alive(self) -> int:
        with self.lock:
            return len(self.replicas) - len(self._down)

    def alive_indices(self) -> list[int]:
        with self.lock:
            return [i for i in range(len(self.replicas))
                    if i not in self._down]

    def home(self, tenant: str) -> int:
        """Tenant's home replica: crc32 % n walked FORWARD past downed
        replicas — the same stable coordination-free placement as the
        in-bank device hash, and tenants of a downed replica spread
        across the survivors instead of piling onto one."""
        n = len(self.replicas)
        with self.lock:
            if len(self._down) >= n:
                raise ReplicaDown("no replica alive")
            idx = zlib.crc32(tenant.encode()) % n
            while idx in self._down:
                idx = (idx + 1) % n
            return idx

    def mark_down(self, index: int) -> None:
        """Record a replica as dead; its tenants re-home on the next
        routing decision. Marking is one-way — a rejoining process is
        a NEW replica list, not a resurrection (its bank state is
        cold and its bulletin cursor stale)."""
        with self.lock:
            if index not in self._down:
                self._down.add(index)
                counters.inc("serve.replica_down")

    # -- epoch bulletin ---------------------------------------------------

    def publish_feedback(self, base: str, filt) -> int:
        """Record (base, filt) on the bulletin and eagerly install it
        on every live replica. Returns base's new epoch on the LAST
        replica installed (epochs advance independently per replica;
        the serve layer reports one representative value, as before).

        The bulletin entry is recorded FIRST, under the front lock, so
        a submit racing this publish either sees the entry in
        `_sync_epochs` or arrives after the eager install below — no
        interleaving lets a replica score the tenant pre-bump once
        this call returns."""
        with self.lock:
            self._seq += 1
            seq = self._seq
            self._bulletin[base] = (seq, filt)
            targets = self.alive_indices()
            epoch = 0
            for i in targets:
                epoch = self._install(i, base, seq, filt)
        counters.inc("serve.replica_publish")
        return epoch

    # The serve layer's duck-typed install entry (oa/serve.py holds
    # front.lock around this, mirroring the single-service path).
    def apply_feedback_filter(self, base: str, filt) -> int:
        return self.publish_feedback(base, filt)

    # lint: holds[lock] -- called from publish_feedback / _sync_epochs, both inside `with self.lock`
    def _install(self, index: int, base: str, seq: int, filt) -> int:
        svc = self.replicas[index]
        with svc.lock:
            epoch = svc.apply_feedback_filter(base, filt)
        self._applied[(index, base)] = seq
        return epoch

    def _sync_epochs(self, index: int, tenants: set[str]) -> None:
        """Apply every bulletin entry covering `tenants` that replica
        `index` has not installed yet — the pre-dispatch replay that
        makes bump-before-next-score structural (module docstring)."""
        with self.lock:
            for base, (seq, filt) in self._bulletin.items():
                prefix = base + "/"
                if self._applied.get((index, base), 0) >= seq:
                    continue
                if any(t == base or t.startswith(prefix)
                       for t in tenants):
                    self._install(index, base, seq, filt)
                    counters.inc("serve.replica_sync_installs")

    # -- scoring ----------------------------------------------------------

    def submit(self, requests: list, *, tol: float, max_results: int,
               deadline=None) -> list:
        """Route the batch to each tenant's home replica, sync pending
        bulletin entries there, and dispatch per-replica waves.
        Results come back in request order. A replica that dies
        mid-wave (`ReplicaDown`) is marked down and its wave re-routes
        to the survivors (`serve.replica_failover`); admission
        refusals (Overloaded / DeadlineExceeded / BankRefusal)
        propagate unchanged — shedding one replica's wave sheds the
        batch, same 503 semantics as the single-service path."""
        out: list = [None] * len(requests)
        pending: dict[int, list[int]] = {}
        for i, req in enumerate(requests):
            pending.setdefault(self.home(req.tenant), []).append(i)
        while pending:
            index, idxs = next(iter(pending.items()))
            del pending[index]
            wave = [requests[i] for i in idxs]
            self._sync_epochs(index, {r.tenant for r in wave})
            try:
                results = self.replicas[index].submit(
                    wave, tol=tol, max_results=max_results,
                    deadline=deadline)
            except ReplicaDown:
                # Re-home this wave's tenants over the survivors and
                # put the re-routed waves back on the worklist (their
                # bulletin sync runs on the NEW home before dispatch).
                self.mark_down(index)
                counters.inc("serve.replica_failover")
                counters.inc("serve.replica_failover_requests",
                             len(idxs))
                for i in idxs:
                    pending.setdefault(
                        self.home(requests[i].tenant), []).append(i)
                continue
            for i, res in zip(idxs, results):
                out[i] = res
        return out  # type: ignore[return-value]

    # -- duck-typed BankService surface -----------------------------------

    @property
    def max_batch_requests(self) -> int:
        return self.replicas[0].max_batch_requests

    @property
    def max_queue_depth(self) -> int:
        return self.replicas[0].max_queue_depth

    @property
    def request_deadline_s(self) -> float:
        return self.replicas[0].request_deadline_s

    @property
    def peak_depth(self) -> int:
        return max(s.peak_depth for s in self.replicas)

    def admission_stats(self) -> dict:
        alive = self.alive_indices()
        per = [self.replicas[i].admission_stats() for i in alive]
        agg = dict(per[0]) if per else {}
        if per:
            agg["queue_depth"] = sum(p["queue_depth"] for p in per)
            agg["queue_depth_peak"] = max(p["queue_depth_peak"]
                                          for p in per)
        agg["replicas"] = len(self.replicas)
        agg["replicas_alive"] = len(alive)
        agg["replicas_down"] = len(self.replicas) - len(alive)
        return agg

    def cache_stats(self) -> dict:
        alive = self.alive_indices()
        stats = [self.replicas[i].cache_stats() for i in alive]
        agg = dict(stats[0]) if stats else {"entries": 0}
        if stats:
            agg["entries"] = sum(s["entries"] for s in stats)
        agg["replicas_alive"] = len(alive)
        return agg

    def tier_stats(self) -> dict:
        """Per-tier residency aggregated across live replicas — the
        front's contribution to GET /bank/stats (oa/serve.py)."""
        alive = self.alive_indices()
        per = {f"r{i}": self.replicas[i].bank.tier_stats()
               for i in alive}
        return {"replicas": len(self.replicas),
                "replicas_alive": len(alive),
                "per_replica": per}
