"""Serving layer (r12): the device-resident model bank.

ONI's product shape is one (θ, φ) model per datatype × day — and the
north star multiplies that by tenant. The batch pipelines in
`onix/pipelines` assume exactly one model at a time; this package is
the piece that turns the scorer into a SERVICE: many tenants' tables
stacked into bank-shaped device arrays, mixed-tenant request batches
scored through ONE jitted program, LRU residency for banks larger than
device memory, and a load harness that replays skewed tenant traffic
(docs/PERF.md "model bank").
"""

from onix.serving.model_bank import (BankRefusal, BankService, ModelBank,
                                     ScoreRequest, TenantModel)

__all__ = ["BankRefusal", "BankService", "ModelBank", "ScoreRequest",
           "TenantModel"]
