"""Device-resident model bank: one batched program scores N tenants.

The single-tenant scoring path (`onix/models/scoring.py`, `oa/serve.py`)
costs N separate dispatches, N H2D transfers, and N compiled-program
round-trips for N tenants — fatal at "millions of users" where the
model axis is per-datatype × per-day × per-tenant. The bank makes the
per-model axis a batched ARRAY dimension instead of a host-side loop
(the AD-LDA decomposition argument, arxiv 0909.4603, applied to
serving): tenants' (θ, φ) tables are stacked/padded into bank-shaped
device arrays

    theta_bank [B, D_pad, K]      phi_bank [B, V_pad, K]

grouped by a pow2 pad ladder (`onix/models/compaction.pow2_bucket`) so
tenants of similar size share one compiled shape class, and a
mixed-tenant request batch is scored by ONE jitted program: a
tenant-slot gather feeding the exact chunked bottom-M machinery of
`scoring._scan_bottom_k`, so per-tenant winners are bit-identical to
the single-tenant `top_suspicious` path (asserted in
tests/test_model_bank.py and per-run in bench.py's `model_bank`
component).

Two batched forms, gated like the n_wk count-update forms:

* ``vmap``   — `jax.vmap` over the request axis; each lane slices its
  tenant's tables out of the bank (`theta_bank[slot]`) and runs the
  shared scan. The bank axis rides XLA's batched gather.
* ``gather`` — the bank flattens to [(B·D_pad), K] and every EVENT
  gathers through a flat tenant-composed index `slot·D_pad + d`; one
  fused stream scores all requests, then the same bottom-M machinery
  selects per request row. No per-request table slice ever
  materializes.

Both forms compute `score_events`' exact gather-dot, so winners are
bit-identical between forms AND against the single-tenant scan; the
choice is pure performance. `_BANK_GATHER_MIN_EVENTS` is the measured
per-backend crossover (events per dispatch), `ONIX_BANK_FORM` pins a
form for experiments, and unmeasured backends keep the vmap default
(docs/BANK_r12_cpu.json; TPU rows queued in docs/TPU_QUEUE.json).

Residency: each shape class holds a fixed number of resident slots
(`capacity`). Admission stages ALL newly-needed tenants of a request
batch host-side and ships ONE `device_put` per table family (not
per-tenant round-trips); eviction is LRU and happens ONLY at request
batch boundaries — a tenant's tables can never change mid-scan, so a
capped bank's winners are identical to an uncapped run (tested, and
proven at harness scale in scripts/exp_model_bank.py). Admits, evicts,
hits, H2D bytes/transfers, and dispatches are all counted in
`onix.utils.obs.counters` under ``bank.*``.

Sharding (r20): the bank optionally spreads its shape-class banks over
a dp device mesh by TENANT HASH — each tenant's tables live wholly on
its stable home device (crc32 placement), a mixed-tenant batch splits
into per-device waves, and each wave dispatches as an INDEPENDENT
device program. No array is ever partitioned across devices, so the
compiled scoring HLO is psum-free BY CONSTRUCTION (asserted: the first
compile of every sharded shape is scanned for collective ops), and
per-tenant winners are bit-identical to the single-device bank — the
same `_scan_bottom_k` runs over the same per-tenant tables, only the
device it runs on changes (the AD-LDA locality argument, arxiv
0909.4603, applied one level up: placement, not decomposition).
`select_shard_form` gates single vs sharded through the shared
`resolve_form_gate` chain; `_BANK_SHARD_MIN_TENANTS` starts EMPTY per
the r15 discipline, so auto resolves single-device everywhere until
the queued TPU crossover lands (docs/TPU_QUEUE.json
`bank_sharded_tpu`).

Residency tiers (r20): three explicit tiers — HBM (shard slots), host
RAM (`_models`, bounded by `host_capacity`), disk (`bulk_loader` →
`checkpoint.load_models`). A demand-tracked PREFETCHER sits between
disk and the host tier: per-tenant request counts decay into a Zipf
demand estimate, and at request-batch boundaries the hottest
not-host-resident tenants are promoted in one bulk pass
(`bank.prefetch_*` counters; chaos site `bank:prefetch` fires at
entry, pre-mutation, so one bounded retry replays safely — and the
prefetch is best-effort: exhaustion never fails scoring). Device
admission is untouched: one `device_put` per table family per wave
boundary, exactly as before.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
import zlib
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from onix.config import resolve_form_gate
from onix.feedback.filter import (FILTER_FLOOR, FilterTables, HostFilter,
                                  _pad_sorted, apply_filter, split_key)
from onix.models.compaction import pow2_bucket
from onix.models.scoring import TopK, _scan_bottom_k, _subscan_scores, score_events
from onix.utils import faults, telemetry
from onix.utils.obs import counters
from onix.utils.resilience import (Deadline, DeadlineExceeded, Overloaded,
                                   RetryPolicy, retry_call)

# Bounded absorb-and-replay budgets for the serve-path fault sites
# (docs/ROBUSTNESS.md "serving resilience"). Injected faults fire at
# ENTRY points — before any cache/residency/filter mutation — so one
# bounded retry replays the call safely (the stream:batch discipline);
# zero backoff because the sites are in-process, not I/O.
_SERVE_RETRY = RetryPolicy(max_attempts=2, base_backoff_s=0.0, jitter=0.0,
                           salvage_on_final=False)
# Model loads ARE I/O (models_dir may be network-backed): transient
# OSErrors get one backed-off retry, then the batch is REFUSED
# (BankRefusal) instead of wedging on a dead filesystem.
_LOAD_RETRY = RetryPolicy(max_attempts=2, base_backoff_s=0.05,
                          max_backoff_s=1.0, jitter=0.0,
                          salvage_on_final=False)

# Pad floors for the bank shape ladder: smallest [D_pad]/[V_pad] a
# tenant occupies. Low floors would mint a compiled shape class per
# tiny tenant; high floors waste bank HBM on padding. 256 keeps the
# ladder at most log2(D_max/256) classes deep.
BANK_DOC_FLOOR = 256
BANK_VOCAB_FLOOR = 256
# Pow2 floor for the per-request event axis (requests pad up to the
# smallest covering pow2 so the jit cache stays bounded).
BANK_EVENTS_FLOOR = 64

# Measured crossover: total (padded) events per dispatch above which
# the flat tenant-gather form beats the vmap form. Keyed by backend
# like lda_gibbs._NWK_MATMUL_MIN_DENSITY; an ABSENT backend keeps the
# vmap default (never an unmeasured guess). cpu: 0 — the gather form
# won at EVERY dispatch size measured on this host (1.5k..512k events
# per dispatch, bank sizes 4..64: 1.7-6x over vmap; the vmap lanes
# batch-gather whole [D_pad, K] table slices where the flat form
# gathers exactly the 2K-float rows each event touches —
# docs/BANK_r12_cpu.json `bank_size_ladder`). tpu: ABSENT until the
# queued crossover lands (docs/TPU_QUEUE.json `model_bank_tpu`) — the
# vmap default rides XLA's batched gather there, and the CPU result
# must not be assumed to transfer.
_BANK_GATHER_MIN_EVENTS = {
    "cpu": 0,
}


def select_bank_form(form: str, n_requests: int, n_pad: int,
                     backend: str | None = None) -> str:
    """Resolve the batched scoring form for one dispatch.

    Priority (config.resolve_form_gate — the ONE precedence chain
    shared with `select_nwk_form` and `pallas_serve.select_serve_form`
    so the three gate tables cannot drift): ONIX_BANK_FORM env
    override > explicit config form > the measured
    `_BANK_GATHER_MIN_EVENTS` table for this backend > vmap. The forms
    are bit-identical, so this is pure performance and safe to flip
    between dispatches."""
    def measured() -> str | None:
        b = backend if backend is not None else jax.default_backend()
        min_events = _BANK_GATHER_MIN_EVENTS.get(b)
        if min_events is not None and n_requests * n_pad >= min_events:
            return "gather"
        return None

    return resolve_form_gate(gate="bank form", choices=("vmap", "gather"),
                             explicit=form, env_var="ONIX_BANK_FORM",
                             measured=measured, default="vmap")


# Measured crossover for the r20 sharded placement: registered tenants
# above which spreading the shape-class banks over the dp mesh beats
# one device (per-device waves dispatch independently, so the win is
# parallel occupancy minus the per-device compile + admission
# duplication). Keyed by backend like `_BANK_GATHER_MIN_EVENTS`;
# DELIBERATELY EMPTY for every backend — cpu included — until the
# queued TPU rows land (docs/TPU_QUEUE.json `bank_sharded_tpu`): this
# 2-core host's virtual devices share the same cores, so a CPU
# "crossover" would be scheduler noise, never a chip decision. Auto
# therefore resolves single-device everywhere today; the forms are
# bit-identical, so pinning `sharded` (config or ONIX_BANK_SHARD) is
# always safe.
_BANK_SHARD_MIN_TENANTS: dict[str, int] = {}


def select_shard_form(form: str, n_tenants: int, n_devices: int,
                      backend: str | None = None) -> str:
    """Resolve the bank placement form: "single" (every tenant on the
    default device — the pre-r20 shape) vs "sharded" (tenant-hash
    placement over the mesh). Same precedence chain as every measured
    gate (config.resolve_form_gate): ONIX_BANK_SHARD env override >
    explicit config form > the measured `_BANK_SHARD_MIN_TENANTS`
    table > single. Resolved ONCE per bank (first score) and frozen —
    placement keys device residency, so flipping mid-life would strand
    resident tenants on devices the router no longer picks."""
    def measured() -> str | None:
        b = backend if backend is not None else jax.default_backend()
        min_tenants = _BANK_SHARD_MIN_TENANTS.get(b)
        if min_tenants is not None and n_devices >= 2 \
                and n_tenants >= min_tenants:
            return "sharded"
        return None

    return resolve_form_gate(gate="bank shard", choices=("single", "sharded"),
                             explicit=form, env_var="ONIX_BANK_SHARD",
                             measured=measured, default="single")


#: Substrings that name a cross-device collective in optimized HLO.
#: The sharded bank's psum-free-by-construction claim is machine-
#: checked against these: every per-device wave is an independent
#: single-device program, so NONE may appear in its compiled text.
_COLLECTIVE_MARKERS = ("all-reduce", "all-gather", "all-to-all",
                       "collective-permute", "reduce-scatter",
                       "collective-broadcast")


def assert_collective_free(kernel, args, *, max_results: int) -> None:
    """Compile `kernel` for `args` and assert the optimized HLO names
    no cross-device collective (`_COLLECTIVE_MARKERS`). Cheap where it
    runs: lowering hits the same jit cache the scoring call populates,
    so the text render is the only extra work — and it runs once per
    compiled shape (the caller's `collective_checked` set)."""
    txt = kernel.lower(*args, max_results=max_results).compile().as_text()
    found = [m for m in _COLLECTIVE_MARKERS if m in txt]
    if found:
        raise AssertionError(
            f"sharded bank program compiled a cross-device collective "
            f"({', '.join(found)}) — per-device waves must be "
            "independent single-device programs")


class BankRefusal(ValueError):
    """A request the bank refuses to score (unknown tenant, out-of-range
    token ids, or a single batch needing more distinct tenants than the
    residency capacity). Refusal semantics: the request is REJECTED
    before any device work — never scored against wrong or padded
    tables (docs/ROBUSTNESS.md "model bank refusals")."""


@dataclasses.dataclass(frozen=True)
class TenantModel:
    """One tenant's fitted tables, host-side (f32 [D,K] / [V,K]).
    `epoch` is the persisted model epoch (checkpoint meta
    `model_epoch`) — 0 for a fresh fit, bumped by online feedback
    updates; the bank's winner-cache invalidation keys on it."""
    theta: np.ndarray
    phi_wk: np.ndarray
    epoch: int = 0

    @property
    def n_docs(self) -> int:
        return int(self.theta.shape[0])

    @property
    def n_vocab(self) -> int:
        return int(self.phi_wk.shape[0])

    @property
    def n_topics(self) -> int:
        return int(self.theta.shape[1])


@dataclasses.dataclass
class ScoreRequest:
    """One (tenant, window) scoring request: bottom-`max_results`
    suspicious events among the request's (doc, word) tokens, exactly
    the single-tenant `top_suspicious` contract. `window` identifies an
    immutable replay window for the serve layer's winner cache; None
    disables caching for the request."""
    tenant: str
    doc_ids: np.ndarray
    word_ids: np.ndarray
    window: str | None = None


# ---------------------------------------------------------------------------
# The two batched kernels. Both end in scoring's _scan_bottom_k, so the
# merge/tie/sentinel semantics (-1 on unfilled slots, lower-index wins
# ties) are the single-tenant scan's by construction. Both apply the
# per-tenant NOISE FILTER (r13, onix/feedback/) as the same fused
# post-score adjustment before the tol screen: per request row, four
# sorted sentinel-padded key tables (word/pair × suppress/boost) plus a
# boost scale. A tenant with no feedback rides all-sentinel rows, whose
# membership mask is constant False — scores bit-identical to the
# pre-filter kernels (the filter.py exactness contract, tested).
# ---------------------------------------------------------------------------


def _row_filter_adjust(s, dc, wc, filt):
    """One request row's fused adjustment: word key = the event's word
    id, pair key = the packed (doc, word) identity the serve-layer
    feedback rows label (filter.pack_pair — here as (hi, lo) = (doc,
    word) uint32 halves, the x32-safe rendering)."""
    wl = wc.astype(jnp.uint32)
    wk = (jnp.zeros_like(wl), wl)
    pk = (dc.astype(jnp.uint32), wl)
    return apply_filter(s, wk, pk, filt)


@functools.partial(jax.jit, static_argnames=("max_results",))
def _bank_score_vmap(theta_bank, phi_bank, slots, doc_ids, word_ids, mask,
                     tol, filt_rows, *, max_results: int) -> TopK:
    """vmap form: one lane per request; the lane slices its tenant's
    tables from the bank and runs the shared chunked bottom-M scan
    (chunk = the padded row, so the scan is one merge — identical
    result to the single-tenant path at any chunking). `filt_rows` is
    a FilterTables pytree with a leading request axis on every leaf,
    or None — the static no-feedback fast path that compiles without
    any membership search (a wave with no filtered tenant must cost
    exactly what it did pre-filter)."""
    n_pad = doc_ids.shape[1]

    def make_one(filtered):
        def one(slot, dr, wr, mr, *filt):
            th = theta_bank[slot]
            ph = phi_bank[slot]

            def score_chunk(dc, wc, mc):
                s = _subscan_scores(th, ph, dc, wc)
                if filtered:
                    s = _row_filter_adjust(s, dc, wc, filt[0])
                return jnp.where((mc > 0) & (s < tol), s, jnp.inf)

            return _scan_bottom_k((dr, wr, mr), n_pad, score_chunk,
                                  max_results=max_results, chunk=n_pad)
        return one

    if filt_rows is None:
        return jax.vmap(make_one(False))(slots, doc_ids, word_ids, mask)
    return jax.vmap(make_one(True))(slots, doc_ids, word_ids, mask,
                                    filt_rows)


@functools.partial(jax.jit, static_argnames=("max_results",))
def _bank_score_gather(theta_bank, phi_bank, slots, doc_ids, word_ids, mask,
                       tol, filt_rows, *, max_results: int) -> TopK:
    """gather form: the bank flattens to [(B·D_pad), K] and every event
    gathers via the tenant-composed flat index — one fused stream, no
    per-request table slice. Selection reuses the same bottom-M scan
    per request row over the precomputed (masked, filter-adjusted)
    scores. filt_rows=None is the static no-feedback fast path."""
    b, d_pad, _ = theta_bank.shape
    v_pad = phi_bank.shape[1]
    theta_flat = theta_bank.reshape(b * d_pad, -1)
    phi_flat = phi_bank.reshape(b * v_pad, -1)
    n_pad = doc_ids.shape[1]
    gd = (slots[:, None] * jnp.int32(d_pad) + doc_ids).reshape(-1)
    gw = (slots[:, None] * jnp.int32(v_pad) + word_ids).reshape(-1)
    s = score_events(theta_flat, phi_flat, gd, gw).reshape(doc_ids.shape)
    if filt_rows is not None:
        s = jax.vmap(_row_filter_adjust)(s, doc_ids, word_ids, filt_rows)
    s = jnp.where((mask > 0) & (s < tol), s, jnp.inf)

    def sel(sr):
        return _scan_bottom_k((sr,), n_pad, lambda sc: sc,
                              max_results=max_results, chunk=n_pad)

    return jax.vmap(sel)(s)


_BANK_KERNELS = {"vmap": _bank_score_vmap, "gather": _bank_score_gather}


def _bank_kernel_for(form: str, serve: str):
    """The compiled program for one (bank form, serve form) pair. The
    "fused" serve arm swaps the scan+filter stages for the r15
    one-kernel Pallas path (onix/models/pallas_serve.py) — same
    gathers, same scores, same winners, bit-identical (tested); the
    interpret/compile decision rides pallas_serve's shared
    `_default_interpret` (Mosaic on real TPUs, XLA emulation
    elsewhere)."""
    if serve != "fused":
        return _BANK_KERNELS[form]
    from onix.models import pallas_gibbs, pallas_serve
    fused = {"vmap": pallas_serve.bank_score_vmap_fused,
             "gather": pallas_serve.bank_score_gather_fused}[form]
    interpret = pallas_gibbs._default_interpret()
    return functools.partial(fused, interpret=interpret)


class _Shard:
    """One (shape class, home device)'s resident bank: [C, D_pad, K] /
    [C, V_pad, K] device arrays plus the tenant→slot LRU bookkeeping.
    `device` pins the arrays (sharded placement); None keeps jax's
    default device — the pre-r20 single-device shape."""

    def __init__(self, d_pad: int, v_pad: int, k: int, capacity: int,
                 device=None, device_index: int = 0):
        self.d_pad, self.v_pad, self.k = d_pad, v_pad, k
        self.capacity = capacity
        self.device = device
        self.device_index = device_index
        theta = jnp.zeros((capacity, d_pad, k), jnp.float32)
        phi = jnp.zeros((capacity, v_pad, k), jnp.float32)
        if device is not None:
            theta = jax.device_put(theta, device)
            phi = jax.device_put(phi, device)
        self.theta = theta
        self.phi = phi
        self.lru: OrderedDict[str, int] = OrderedDict()  # tenant -> slot
        self.free: list[int] = list(range(capacity - 1, -1, -1))


class ModelBank:
    """The device-resident bank: registry + residency + batched scoring.

    `capacity` is resident tenants PER SHAPE CLASS (tenants land in the
    class of their pow2-padded (D_pad, V_pad, K); same-scale tenants
    share arrays and compiled programs). `loader(tenant)` supplies
    models not in the host registry one at a time; `bulk_loader(names)`
    (the serve layer wires it to `checkpoint.load_models` over
    `serving.models_dir`) fetches a request batch's unknown tenants in
    one host-side pass before scoring. A loader miss is a
    `BankRefusal`. `host_capacity` (0 = unbounded) caps how many
    loader-backed models stay in the HOST registry: beyond it, the
    least-recently-used re-fetchable tenant that is not device-resident
    is dropped (`bank.host_evict`) — without it a long-lived server
    walking the per-datatype × per-day × per-tenant model space grows
    host RAM monotonically. Explicitly `add()`ed models are never
    host-evicted (no loader can bring them back)."""

    def __init__(self, capacity: int = 64, form: str = "auto",
                 loader=None, bulk_loader=None, host_capacity: int = 0,
                 filter_loader=None, epoch_loader=None,
                 serve_form: str = "auto",
                 degrade_form_fallback: bool = True,
                 devices=None, shard_form: str = "auto",
                 prefetch_depth: int = 0):
        if capacity < 1:
            raise ValueError("bank capacity must be >= 1")
        if host_capacity < 0:
            raise ValueError("host_capacity must be >= 0 (0 = unbounded)")
        if prefetch_depth < 0:
            raise ValueError("prefetch_depth must be >= 0 (0 = off)")
        self.capacity = capacity
        self.form = form
        # r15 serving-scan form (serving.serve_form): "xla" | "fused" |
        # "auto" (pallas_serve.select_serve_form — resolves to xla on
        # every backend until a measured crossover lands).
        self.serve_form = serve_form
        self._loader = loader
        self._bulk_loader = bulk_loader
        self._filter_loader = filter_loader
        self._epoch_loader = epoch_loader
        self.host_capacity = host_capacity
        self._models: OrderedDict[str, TenantModel] = OrderedDict()
        self._loader_backed: set[str] = set()
        # Shard key = (D_pad, V_pad, K, home-device index): the r20
        # mesh placement just widens the pre-r20 shape-class key with
        # the tenant-hash device axis (index 0 everywhere when the
        # resolved form is "single").
        self._shards: dict[tuple[int, int, int, int], _Shard] = {}
        # r20 sharded placement. `devices` is the candidate mesh (a
        # jax.devices() subset, order-significant: the crc32 hash
        # indexes into it); None = the default device only. The form
        # resolves LAZILY at first score (select_shard_form — the gate
        # sees the registered-tenant count) and FREEZES: placement
        # keys residency, so it must never flip mid-life.
        self.devices = list(devices) if devices else None
        self.shard_form = shard_form
        self._resolved_shard: str | None = None
        #: Shape keys whose compiled HLO passed the collective-free
        #: scan (sharded mode asserts it once per compiled shape).
        self.collective_checked: set[tuple] = set()
        # r20 host-tier prefetcher: decayed per-tenant request counts
        # (the Zipf demand estimate), the promote budget per batch
        # boundary, and the promoted-but-not-yet-referenced set the
        # hit/waste accounting keys on.
        self.prefetch_depth = prefetch_depth
        self._demand: dict[str, float] = {}
        self._prefetched: set[str] = set()
        self._demand_batches = 0
        # r13 feedback loop: per-tenant compiled noise filter
        # (onix/feedback/filter.HostFilter) + MODEL EPOCH. The epoch
        # bumps on every event that can change a tenant's winners —
        # add() (new/updated tables) and set_filter() — and the serve
        # layer's winner cache keys on it, so post-feedback requests
        # can never be served pre-feedback winners.
        self._filters: dict[str, HostFilter] = {}
        self._epochs: dict[str, int] = {}
        # Last PERSISTED model_epoch seen per tenant (add() adopt/bump
        # logic): distinguishes "same file reloaded" from "new file
        # whose stamp trails the filter-inflated in-memory epoch".
        self._disk_epochs: dict[str, int] = {}
        # Degradation ladder (r16): a failed "fused" dispatch re-runs
        # through the bit-identical xla kernels instead of failing the
        # wave (`serve.form_fallback`; docs/ROBUSTNESS.md "serving
        # resilience"). Winners are identical by the r15 contract.
        self.degrade_form_fallback = degrade_form_fallback
        self.dispatches = 0
        # Per-BANK fallback tally: the service's degraded stamp keys on
        # THIS bank's dispatches, never the process-global counter (two
        # services in one process must not stamp each other degraded).
        self.fallback_dispatches = 0
        self.compiled_shapes: set[tuple] = set()

    # -- registry ---------------------------------------------------------

    def add(self, tenant: str, theta, phi_wk,
            epoch: int | None = None) -> None:
        theta = np.ascontiguousarray(theta, np.float32)
        phi_wk = np.ascontiguousarray(phi_wk, np.float32)
        if theta.ndim != 2 or phi_wk.ndim != 2 \
                or theta.shape[1] != phi_wk.shape[1]:
            raise ValueError(
                f"tenant {tenant!r}: want theta [D,K] / phi_wk [V,K] with a "
                f"shared K, got {theta.shape} / {phi_wk.shape}")
        self._models[tenant] = TenantModel(theta, phi_wk,
                                           epoch=int(epoch or 0))
        # New tables invalidate cached winners. An EXPLICIT epoch is a
        # persisted stamp (loader path): reloading the SAME file after
        # a host-evict (stamp unchanged since last seen) must NOT
        # invalidate its cached winners — but a CHANGED stamp means a
        # genuinely new file, and the in-memory epoch must move PAST
        # its current value even when set_filter bumps (never
        # persisted) have inflated it numerically ahead of the disk
        # stamp; comparing magnitudes alone would let a re-fit hide
        # behind filter bumps and serve pre-refit cached winners. A
        # bare add() means new tables of unknown provenance: always
        # bump.
        cur = self._epochs.get(tenant)
        if epoch is not None:
            prev_disk = self._disk_epochs.get(tenant)
            self._disk_epochs[tenant] = int(epoch)
            if prev_disk is not None and int(epoch) != prev_disk:
                self._epochs[tenant] = max((cur or 0) + 1, int(epoch))
            else:
                self._epochs[tenant] = max(cur or 0, int(epoch))
        else:
            self._epochs[tenant] = (cur + 1) if cur is not None else 0
        # Device residency of the OLD tables must not survive the new
        # ones — evict from every shard (the update may have changed
        # the tenant's shape class) so the next wave re-admits the
        # updated copy.
        for shard in self._shards.values():
            if tenant in shard.lru:
                shard.free.append(shard.lru.pop(tenant))
                counters.inc("bank.evict")

    def epoch(self, tenant: str) -> int:
        """Current model epoch (0 for a tenant never seen)."""
        return self._epochs.get(tenant, 0)

    def set_filter(self, tenant: str, filt: HostFilter | None) -> None:
        """Install (or clear, with None/empty) a tenant's compiled
        noise filter. Always bumps the epoch — the winner cache must
        drop entries scored under the previous filter either way."""
        if filt is None or filt.empty_filter:
            self._filters.pop(tenant, None)
        else:
            self._filters[tenant] = filt
        self._epochs[tenant] = self._epochs.get(tenant, 0) + 1

    def get_filter(self, tenant: str) -> HostFilter | None:
        return self._filters.get(tenant)

    def refresh_from_disk(self, tenant: str) -> None:
        """Adopt an OUT-OF-PROCESS re-save: re-read the tenant's
        persisted epoch stamp (`epoch_loader`, serve wires it to
        checkpoint.model_meta_epoch — one small json read) and, when
        it differs from the last stamp seen, bump the in-memory epoch
        and drop the host copy + device residency so the next score
        loads the NEW tables. Without this, a nudge_and_save or
        re-fit by another process is invisible to a live server — its
        winner cache would serve pre-update winners until restart.
        Only loader-backed tenants refresh (an explicitly add()ed
        model has no file of record to re-fetch)."""
        if self._epoch_loader is None or tenant not in self._loader_backed:
            return
        stamp = self._epoch_loader(tenant)
        prev = self._disk_epochs.get(tenant)
        if stamp is None or prev is None or stamp == prev:
            return
        self._disk_epochs[tenant] = int(stamp)
        self._epochs[tenant] = max(self._epochs.get(tenant, 0) + 1,
                                   int(stamp))
        self._models.pop(tenant, None)
        self._loader_backed.discard(tenant)
        for shard in self._shards.values():
            if tenant in shard.lru:
                shard.free.append(shard.lru.pop(tenant))
                counters.inc("bank.evict")
        counters.inc("bank.disk_epoch_refresh")

    def set_filter_tree(self, base: str, filt: HostFilter | None) -> int:
        """Install the filter on `base` AND every known sub-tenant
        (`base/<sub>`): sub-tenants share the per-(datatype, date)
        feedback CSV — filter_loader compiles them the same filter on
        first load, so the live-update path must reach them too or
        their cached winners would keep serving dismissed events until
        a restart. "Known" = registered models plus tenants that
        already carry a filter; an unloaded sub-tenant still gets the
        filter from filter_loader when it loads. Returns base's new
        epoch."""
        prefix = base + "/"
        targets = {base} | {t for t in
                            set(self._models) | set(self._filters)
                            if t.startswith(prefix)}
        for t in targets:
            self.set_filter(t, filt)
        return self.epoch(base)

    def _load_retried(self, what: str, fn):
        """Drive a model load under the bounded `_LOAD_RETRY` policy.
        Loads are the one serve-path stage that touches a filesystem
        (models_dir may be network-backed), so transient OSErrors get
        one backed-off retry; exhaustion REFUSES with BankRefusal
        (`bank.load_refusal`) instead of wedging the batch — the
        degradation ladder's refuse-never-wedge rung
        (docs/ROBUSTNESS.md "serving resilience"). Non-I/O errors
        (ModelIntegrityError, BankRefusal) propagate untouched: a
        digest mismatch is not transient."""
        try:
            return retry_call(lambda strict: fn(), policy=_LOAD_RETRY,
                              counter_prefix="bank.load",
                              retry_on=OSError)
        except OSError as e:
            counters.inc("bank.load_refusal")
            raise BankRefusal(
                f"{what}: model load failed after "
                f"{_LOAD_RETRY.max_attempts} attempts: {e}") from e

    def model(self, tenant: str) -> TenantModel:
        m = self._models.get(tenant)
        if m is not None:
            self._models.move_to_end(tenant)
        if m is None and self._loader is not None:
            m = self._load_retried(f"tenant {tenant!r}",
                                   lambda: self._loader(tenant))
            if m is not None:
                self.add(tenant, m.theta, m.phi_wk, epoch=m.epoch)
                self._loader_backed.add(tenant)
                self._load_filter(tenant)
                self._trim_host_registry(keep={tenant})
                m = self._models[tenant]
        if m is None:
            raise BankRefusal(f"unknown tenant {tenant!r}")
        return m

    def _load_filter(self, tenant: str) -> None:
        """Attach the tenant's persisted feedback filter on first load
        (serve wires `filter_loader` to the feedback CSV compile), so
        a restarted server suppresses dismissed winners from its very
        first /score — no re-labeling needed."""
        if self._filter_loader is None or tenant in self._filters:
            return
        filt = self._filter_loader(tenant)
        if filt is not None and not filt.empty_filter:
            # Through set_filter — the attach must BUMP the epoch:
            # winner-cache entries for this tenant may predate a
            # host-evict, and they were scored without this filter.
            self.set_filter(tenant, filt)

    def _trim_host_registry(self, keep: set[str] = frozenset()) -> None:
        """Drop the oldest re-fetchable, non-device-resident host
        copies down to `host_capacity` loader-backed entries. Device
        residency is untouched; a dropped tenant simply reloads from
        the loader on its next reference. `keep` names tenants in
        flight (just loaded, not yet admitted) that must survive even
        over the cap."""
        if not self.host_capacity:
            return
        n_backed = len(self._loader_backed)
        if n_backed <= self.host_capacity:
            return
        for t in list(self._models):        # OrderedDict: oldest first
            if n_backed <= self.host_capacity:
                break
            if t in keep or t not in self._loader_backed:
                continue
            if any(t in sh.lru for sh in self._shards.values()):
                continue                    # still on device: keep host copy
            del self._models[t]
            self._loader_backed.discard(t)
            counters.inc("bank.host_evict")
            if t in self._prefetched:
                # Promoted ahead of demand, evicted before any request
                # referenced it: the prefetcher's false positive.
                self._prefetched.discard(t)
                counters.inc("bank.prefetch_waste")
            n_backed -= 1

    # -- host-RAM residency tier: demand-tracked prefetch (r20) -----------

    def _note_demand(self, requests) -> None:
        """Fold one request batch into the decayed per-tenant demand
        counts — the Zipf estimate the prefetcher ranks promotion
        candidates by. Halving every 32 batches (and dropping cold
        entries) keeps the table a bounded sliding window rather than
        an all-time popularity census that could never forget a
        formerly-hot tenant."""
        for req in requests:
            self._demand[req.tenant] = self._demand.get(req.tenant, 0.) + 1.
        self._demand_batches += 1
        if self._demand_batches % 32 == 0:
            self._demand = {t: v / 2 for t, v in self._demand.items()
                            if v >= 0.5}

    def _note_tiers(self, requests) -> None:
        """Per-request residency-tier accounting, BEFORE the batch
        mutates anything: hbm (device-resident), host (registry copy,
        needs admission only), disk (absent — the bulk/bulk-miss
        loaders will fetch it). The /bank/stats per-tier hit/miss
        picture and the harness's per-tier latency classes both read
        these counters."""
        for req in requests:
            t = req.tenant
            if t not in self._models:
                counters.inc("bank.tier_disk_load")
            elif self.resident(t):
                counters.inc("bank.tier_hbm_hit")
                self._touch_prefetched(t)
            else:
                counters.inc("bank.tier_host_hit")
                self._touch_prefetched(t)

    def _touch_prefetched(self, tenant: str) -> None:
        if tenant in self._prefetched:
            self._prefetched.discard(tenant)
            counters.inc("bank.prefetch_hit")

    def prefetch(self, tenants: list[str]) -> int:
        """Promote `tenants` from disk into the host-RAM tier in ONE
        bulk pass (`bulk_loader` → checkpoint.load_models), ahead of
        the demand the Zipf tracker predicts. Chaos site
        `bank:prefetch` fires at ENTRY — before any registry, filter,
        or epoch mutation — so the caller's bounded retry replays the
        whole promotion safely. Returns tenants actually promoted
        (absent-on-disk names are simply skipped: a prefetch is a
        prediction, not a demand)."""
        want = [t for t in tenants if t not in self._models]
        if not want or self._bulk_loader is None:
            return 0
        with telemetry.TRACER.span("bank.prefetch", tenants=len(want)):
            faults.fire("bank", "prefetch")
            loaded = self._load_retried(f"prefetch of {len(want)} tenants",
                                        lambda: self._bulk_loader(want))
            for t, m in loaded.items():
                self.add(t, m.theta, m.phi_wk, epoch=m.epoch)
                self._loader_backed.add(t)
                self._load_filter(t)
                self._prefetched.add(t)
                counters.inc("bank.prefetch_promoted")
            self._trim_host_registry(keep=set(loaded))
        return len(loaded)

    def _maybe_prefetch(self) -> None:
        """One prefetch pass at a request-batch boundary: promote up to
        `prefetch_depth` of the hottest demanded-but-not-host-resident
        tenants. BEST-EFFORT by contract — an injected fault is
        absorbed by one bounded replay, and exhaustion (a second
        injected fault, a dead filesystem) is counted and dropped,
        never surfaced to the scoring path: losing a prefetch costs
        latency on a later miss, failing a scored batch costs answers."""
        if not self.prefetch_depth or self._bulk_loader is None:
            return
        hot = sorted(self._demand.items(), key=lambda kv: -kv[1])
        cands = [t for t, _ in hot if t not in self._models]
        cands = cands[:self.prefetch_depth]
        if not cands:
            return
        counters.inc("bank.prefetch")
        try:
            retry_call(lambda strict: self.prefetch(cands),
                       policy=_SERVE_RETRY, counter_prefix="bank.prefetch",
                       retry_on=faults.InjectedFault)
        except (faults.InjectedFault, BankRefusal):
            counters.inc("bank.prefetch_failed")

    def tier_stats(self) -> dict:
        """The per-tier residency picture `/bank/stats` exposes: HBM
        (shard slots), host RAM (registry copies), disk (loads), plus
        the prefetcher's hit/waste accounting and the resolved
        placement form."""
        hbm_resident = sum(len(sh.lru) for sh in self._shards.values())
        per_device: dict[str, int] = {}
        for sh in self._shards.values():
            key = f"d{sh.device_index}"
            per_device[key] = per_device.get(key, 0) + len(sh.lru)
        return {
            "hbm": {"resident": hbm_resident,
                    "capacity_per_class": self.capacity,
                    "shape_classes": len(self._shards),
                    "per_device_resident": per_device,
                    "hits": counters.get("bank.tier_hbm_hit")},
            "host": {"resident": len(self._models),
                     "loader_backed": len(self._loader_backed),
                     "capacity": self.host_capacity,
                     "hits": counters.get("bank.tier_host_hit"),
                     "evictions": counters.get("bank.host_evict")},
            "disk": {"loads": counters.get("bank.tier_disk_load")},
            "prefetch": {"depth": self.prefetch_depth,
                         "passes": counters.get("bank.prefetch"),
                         "promoted": counters.get("bank.prefetch_promoted"),
                         "hits": counters.get("bank.prefetch_hit"),
                         "waste": counters.get("bank.prefetch_waste"),
                         "failed": counters.get("bank.prefetch_failed"),
                         "tracked_tenants": len(self._demand)},
            "shard_form": self._resolved_shard or "unresolved",
            "n_devices": self.n_devices(),
        }

    def tenants(self) -> list[str]:
        return sorted(self._models)

    def _class_of(self, m: TenantModel) -> tuple[int, int, int]:
        return (pow2_bucket(m.n_docs, BANK_DOC_FLOOR),
                pow2_bucket(m.n_vocab, BANK_VOCAB_FLOOR), m.n_topics)

    # -- sharded placement (r20) ------------------------------------------

    def n_devices(self) -> int:
        return len(self.devices) if self.devices else 1

    def shard_form_resolved(self) -> str:
        """The frozen placement form. First call resolves through the
        gate (env > explicit > measured > single) against the tenant
        count registered AT THAT POINT — placement keys device
        residency, so later registrations must not flip it."""
        if self._resolved_shard is None:
            self._resolved_shard = select_shard_form(
                self.shard_form, n_tenants=len(self._models),
                n_devices=self.n_devices())
            counters.inc(f"bank.shard_form_{self._resolved_shard}")
        return self._resolved_shard

    def _home_index(self, tenant: str) -> int:
        """The tenant's stable home-device index: crc32 placement, so
        every process (and every serve replica) agrees without any
        coordination state. Single form / one device ⇒ always 0."""
        n = self.n_devices()
        if n < 2 or self.shard_form_resolved() != "sharded":
            return 0
        return zlib.crc32(tenant.encode()) % n

    def _device_at(self, index: int):
        return self.devices[index] if self.devices else None

    # -- residency --------------------------------------------------------

    def resident(self, tenant: str) -> bool:
        m = self._models.get(tenant)
        if m is None:
            return False
        shard = self._shards.get(self._class_of(m)
                                 + (self._home_index(tenant),))
        return shard is not None and tenant in shard.lru

    def _ensure_resident(self, shard: _Shard, needed: list[str]) -> None:
        """Admit every tenant in `needed` (distinct, order-preserving)
        into `shard`, LRU-evicting non-needed residents as required.
        Called only at request batch boundaries — the winners-identity
        argument for capped banks rests on that."""
        # Chaos site `bank:admit` fires BEFORE any LRU mutation or H2D
        # staging, so the bounded retry in _score_wave replays the
        # whole admission safely (the stream:batch discipline). The
        # span wraps the site: an injected admission fault closes as an
        # error span, which is exactly the flight-recorder breadcrumb
        # a faults-marker postmortem needs.
        with telemetry.TRACER.span("bank.admit", tenants=len(needed)):
            faults.fire("bank", "admit")
            self._admit_locked(shard, needed)

    def _admit_locked(self, shard: _Shard, needed: list[str]) -> None:
        missing = [t for t in needed if t not in shard.lru]
        for t in needed:
            if t in shard.lru:
                shard.lru.move_to_end(t)
                counters.inc("bank.resident_hit")
        if not missing:
            return
        if len(needed) > shard.capacity:
            raise BankRefusal(
                f"request batch needs {len(needed)} distinct tenants in one "
                f"shape class; residency capacity is {shard.capacity} "
                "(split the batch)")
        needed_set = set(needed)
        while len(shard.free) < len(missing):
            for t in shard.lru:        # OrderedDict: oldest first
                if t not in needed_set:
                    shard.free.append(shard.lru.pop(t))
                    counters.inc("bank.evict")
                    break
        # Stage ALL admits host-side and ship ONE device_put per table
        # family — the bank-aware bulk load (never B round-trips).
        n = len(missing)
        th = np.zeros((n, shard.d_pad, shard.k), np.float32)
        ph = np.zeros((n, shard.v_pad, shard.k), np.float32)
        slots = np.empty(n, np.int32)
        for i, t in enumerate(missing):
            m = self.model(t)   # not _models[]: a tiny host_capacity may
                                # have trimmed a copy loaded this batch
            th[i, :m.n_docs] = m.theta
            ph[i, :m.n_vocab] = m.phi_wk
            slots[i] = shard.free.pop()
            shard.lru[t] = int(slots[i])
            counters.inc("bank.admit")
        # device=None (single form) keeps jax's default placement —
        # the pre-r20 shape; a sharded shard stages straight onto the
        # wave's home device, still ONE transfer per table family.
        th_d = jax.device_put(th, shard.device)
        ph_d = jax.device_put(ph, shard.device)
        counters.inc("bank.h2d_transfers", 2)
        counters.inc("bank.h2d_bytes", th.nbytes + ph.nbytes)
        idx = jnp.asarray(slots)
        shard.theta = shard.theta.at[idx].set(th_d)
        shard.phi = shard.phi.at[idx].set(ph_d)

    # -- scoring ----------------------------------------------------------

    def _validate(self, req: ScoreRequest, m: TenantModel) -> None:
        d = np.asarray(req.doc_ids)
        w = np.asarray(req.word_ids)
        if d.shape != w.shape or d.ndim != 1:
            raise BankRefusal(
                f"tenant {req.tenant!r}: doc_ids/word_ids must be equal-"
                f"length 1-d arrays, got {d.shape} / {w.shape}")
        if d.size and (int(d.min()) < 0 or int(d.max()) >= m.n_docs
                       or int(w.min()) < 0 or int(w.max()) >= m.n_vocab):
            # Out-of-range ids would gather PADDING rows (score 0 — a
            # fabricated top winner). Refuse, never clamp.
            raise BankRefusal(
                f"tenant {req.tenant!r}: token ids out of range for its "
                f"model (D={m.n_docs}, V={m.n_vocab})")

    def score_batch(self, requests: list[ScoreRequest], *, tol: float,
                    max_results: int) -> list[TopK]:
        """Score a mixed-tenant request batch; returns host-side TopK
        per request, in request order. Requests group by shape class
        and split into residency-capacity waves; each wave is ONE
        jitted dispatch (the N→1 collapse the bank exists for)."""
        out: list[TopK | None] = [None] * len(requests)
        # Tier + demand accounting first, BEFORE the bulk load mutates
        # the registry — "which tier answered this request" is a
        # property of the bank's state at receipt.
        self._note_tiers(requests)
        self._note_demand(requests)
        if self._bulk_loader is not None:
            # Fetch the batch's unknown tenants in ONE host-side pass
            # (checkpoint.load_models) instead of per-tenant loader
            # round-trips; model() below still backstops stragglers.
            unknown: list[str] = []
            for req in requests:
                if req.tenant not in self._models \
                        and req.tenant not in unknown:
                    unknown.append(req.tenant)
            if unknown:
                loaded = self._load_retried(
                    f"{len(unknown)} tenants",
                    lambda: self._bulk_loader(unknown))
                for t, m in loaded.items():
                    self.add(t, m.theta, m.phi_wk, epoch=m.epoch)
                    self._loader_backed.add(t)
                    self._load_filter(t)
                self._trim_host_registry(
                    keep={req.tenant for req in requests})
        # Group by (shape class, home device): the r20 placement axis
        # rides the same grouping the shape ladder always used. With
        # the single form every home index is 0 — the pre-r20 shape.
        by_group: dict[tuple, list[int]] = {}
        for i, req in enumerate(requests):
            m = self.model(req.tenant)
            self._validate(req, m)
            key = self._class_of(m) + (self._home_index(req.tenant),)
            by_group.setdefault(key, []).append(i)
        sharded = self.shard_form_resolved() == "sharded" \
            and self.n_devices() > 1
        pending: list[tuple[TopK, list[int]]] = []
        for key, idxs in by_group.items():
            shard = self._shards.get(key)
            if shard is None:
                shard = self._shards[key] = _Shard(
                    *key[:3], self.capacity,
                    device=self._device_at(key[3]), device_index=key[3])
            for wave in self._waves(requests, idxs, shard.capacity):
                if sharded:
                    # Dispatch phase: launch the wave's independent
                    # device program and move on — jax dispatch is
                    # async, so waves routed to different devices
                    # overlap; the winner fetches drain afterwards.
                    with telemetry.TRACER.span("bank.wave",
                                               device=key[3],
                                               requests=len(wave)):
                        res = self._dispatch_wave(shard, requests, wave,
                                                  tol=tol,
                                                  max_results=max_results)
                    counters.inc(f"bank.wave.d{key[3]}")
                    pending.append((res, wave))
                else:
                    self._score_wave(shard, requests, wave, out, tol=tol,
                                     max_results=max_results)
        for res, wave in pending:
            # Fetch phase (sharded): drain in dispatch order; the wall
            # spent blocked here is the cross-device stall the
            # artifact's accounting reports.
            t_fetch = time.perf_counter()
            self._fetch_wave(res, wave, out)
            counters.inc("bank.fetch_wait_us",
                         int((time.perf_counter() - t_fetch) * 1e6))
        # Device eviction above may have freed host copies for trimming
        # (request-batch boundary — same place residency may change).
        self._trim_host_registry()
        # Prefetch at the batch boundary: promote predicted-hot tenants
        # into the host tier so the NEXT batch's misses start warm.
        self._maybe_prefetch()
        return out  # type: ignore[return-value]

    @staticmethod
    def _waves(requests, idxs: list[int], capacity: int):
        """Split one class's request indices into waves of <= capacity
        distinct tenants, preserving order (eviction then happens only
        BETWEEN waves — request boundaries)."""
        wave: list[int] = []
        tenants: set[str] = set()
        for i in idxs:
            t = requests[i].tenant
            if t not in tenants and len(tenants) == capacity:
                yield wave
                wave, tenants = [], set()
            wave.append(i)
            tenants.add(t)
        if wave:
            yield wave

    def _filter_rows(self, requests, wave: list[int],
                     r_pad: int) -> FilterTables:
        """Stack the wave's per-tenant filter tables into a
        FilterTables pytree with a leading [r_pad] request axis: per
        family a ([r_pad, F] hi, [r_pad, F] lo) uint32 pair of sorted
        sentinel-padded rows, plus the per-row boost scale. F is the
        pow2 cover of the wave's largest table per family (floor
        FILTER_FLOOR), so no-feedback waves stay in one tiny shape
        class and the key-table ladder adds O(log entries) compiles."""
        filts = [self._filters.get(requests[i].tenant) for i in wave]

        def fam_rows(fam):
            f_pad = pow2_bucket(
                max([FILTER_FLOOR]
                    + [len(getattr(x, fam)) for x in filts if x]),
                FILTER_FLOOR)
            rows = np.tile(_pad_sorted(np.empty(0, np.uint64), f_pad),
                           (r_pad, 1))
            for row, x in enumerate(filts):
                if x is not None:
                    keys = getattr(x, fam)
                    rows[row, :len(keys)] = keys
            hi, lo = split_key(rows.ravel())
            return (jnp.asarray(hi.reshape(r_pad, f_pad)),
                    jnp.asarray(lo.reshape(r_pad, f_pad)))

        scale = np.ones(r_pad, np.float32)
        for row, x in enumerate(filts):
            if x is not None:
                scale[row] = x.boost_scale
        return FilterTables(word_suppress=fam_rows("word_suppress"),
                            word_boost=fam_rows("word_boost"),
                            pair_suppress=fam_rows("pair_suppress"),
                            pair_boost=fam_rows("pair_boost"),
                            boost_scale=jnp.asarray(scale))

    def _prepare_wave(self, shard: _Shard, requests, wave: list[int], *,
                      tol: float, max_results: int):
        """Admission + host-side staging for one wave: returns the
        kernel args plus the resolved (form, serve) pair and the shape
        key. Shared verbatim by the single-device path (_score_wave)
        and the sharded dispatch phase (_dispatch_wave) — the
        bit-identity argument between the two is that everything
        except the device the program runs on comes from here."""
        needed: list[str] = []
        for i in wave:
            if requests[i].tenant not in needed:
                needed.append(requests[i].tenant)
        # One bounded replay for injected admission faults (the site
        # fires at _ensure_resident entry, pre-mutation); real load
        # I/O failures are retried-then-refused inside _load_retried.
        retry_call(lambda strict: self._ensure_resident(shard, needed),
                   policy=_SERVE_RETRY, counter_prefix="bank.admit",
                   retry_on=faults.InjectedFault)

        r = len(wave)
        n_events = [int(np.asarray(requests[i].doc_ids).size) for i in wave]
        n_pad = pow2_bucket(max(n_events), BANK_EVENTS_FLOOR)
        r_pad = pow2_bucket(r, 1)
        d = np.zeros((r_pad, n_pad), np.int32)
        w = np.zeros((r_pad, n_pad), np.int32)
        m = np.zeros((r_pad, n_pad), np.float32)
        slots = np.zeros(r_pad, np.int32)
        for row, i in enumerate(wave):
            n = n_events[row]
            d[row, :n] = np.asarray(requests[i].doc_ids, np.int32)
            w[row, :n] = np.asarray(requests[i].word_ids, np.int32)
            m[row, :n] = 1.0
            slots[row] = shard.lru[requests[i].tenant]
        # Static no-feedback fast path: a wave with no filtered tenant
        # ships filt_rows=None and compiles WITHOUT the membership
        # search — identical cost to the pre-filter kernels (the
        # common case; the filtered variant is its own compiled shape).
        if any(requests[i].tenant in self._filters for i in wave):
            filt_rows = self._filter_rows(requests, wave, r_pad)
            filt_dims = (filt_rows.word_suppress[0].shape[1],
                         filt_rows.word_boost[0].shape[1],
                         filt_rows.pair_suppress[0].shape[1],
                         filt_rows.pair_boost[0].shape[1])
        else:
            filt_rows, filt_dims = None, None

        form = select_bank_form(self.form, r_pad, n_pad)
        from onix.models.pallas_serve import select_serve_form
        # Gate on n_pad — the PER-LANE event count each fused kernel
        # actually runs at — so the crossover table keeps one unit
        # (per-scan events) across every consumer; the seeding bench
        # row measures a single scan at exactly that unit.
        serve = select_serve_form(self.serve_form, n_pad)
        # The RESOLVED serve form joins the shape key so manifests and
        # bench stamps record what actually compiled (acceptance: gate
        # artifacts must name the arm, not the request).
        shape_key = (form, serve, shard.d_pad, shard.v_pad, shard.k,
                     r_pad, n_pad, max_results, filt_dims)
        self.compiled_shapes.add(shape_key)
        args = (shard.theta, shard.phi, jnp.asarray(slots), jnp.asarray(d),
                jnp.asarray(w), jnp.asarray(m), jnp.float32(tol),
                filt_rows)
        return args, form, serve, shape_key, r, sum(n_events)

    def _launch(self, args, form: str, serve: str, shape_key: tuple, *,
                max_results: int) -> TopK:
        """One wave's kernel call (device-side result — the caller
        fetches) behind the r16 degradation ladder."""
        try:
            res = _bank_kernel_for(form, serve)(
                *args, max_results=max_results)
        except Exception:                   # noqa: BLE001 — the
            # degradation ladder's first rung: a fused-kernel
            # failure (Mosaic lowering, VMEM overflow, injected
            # chaos) falls back to the bit-identical xla kernels —
            # same winners by the r15 identity contract — instead
            # of failing the wave. Counted + stamped degraded
            # upstream; never silent.
            if serve != "fused" or not self.degrade_form_fallback:
                raise
            counters.inc("serve.form_fallback")
            self.fallback_dispatches += 1
            self.compiled_shapes.add(shape_key[:1] + ("xla",)
                                     + shape_key[2:])
            res = _bank_kernel_for(form, "xla")(
                *args, max_results=max_results)
        self.dispatches += 1
        counters.inc("bank.dispatch")
        return res

    def _score_wave(self, shard: _Shard, requests, wave: list[int],
                    out: list, *, tol: float, max_results: int) -> None:
        """The single-device wave: prepare + launch + fetch, all under
        the pre-r20 `bank.score_wave` span (one batched program + ONE
        winner fetch — the latency building block every serve-side
        quantile decomposes into; attrs carry the resolved forms so a
        slow trace names the arm that compiled, not the request)."""
        args, form, serve, shape_key, r, events = self._prepare_wave(
            shard, requests, wave, tol=tol, max_results=max_results)
        with telemetry.TRACER.span("bank.score_wave", form=form,
                                   serve=serve, requests=r,
                                   events=events):
            res = self._launch(args, form, serve, shape_key,
                               max_results=max_results)
            counters.inc("bank.requests", r)
            counters.inc("bank.events", events)
            self._fetch_wave(res, wave, out)

    def _dispatch_wave(self, shard: _Shard, requests, wave: list[int], *,
                       tol: float, max_results: int) -> TopK:
        """The sharded dispatch phase: prepare + launch WITHOUT the
        fetch — jax's async dispatch returns as soon as the program is
        enqueued on the wave's home device, so the caller can launch
        the next device's wave before this one drains. The first
        launch of every shape also proves the psum-free claim: the
        compiled HLO is scanned for cross-device collectives
        (`assert_collective_free`), once per shape key."""
        args, form, serve, shape_key, r, events = self._prepare_wave(
            shard, requests, wave, tol=tol, max_results=max_results)
        if shape_key not in self.collective_checked:
            kernel = _bank_kernel_for(form, serve)
            # The fused arm is a pallas partial without .lower(); its
            # collective-freedom follows from the xla twin it falls
            # back to (same args, same single-device placement).
            if hasattr(kernel, "lower"):
                assert_collective_free(kernel, args,
                                       max_results=max_results)
                counters.inc("bank.collective_checks")
            self.collective_checked.add(shape_key)
        res = self._launch(args, form, serve, shape_key,
                           max_results=max_results)
        counters.inc("bank.requests", r)
        counters.inc("bank.events", events)
        return res

    @staticmethod
    def _fetch_wave(res: TopK, wave: list[int], out: list) -> None:
        scores = np.asarray(res.scores)        # ONE fetch per dispatch
        indices = np.asarray(res.indices)
        for row, i in enumerate(wave):
            out[i] = TopK(scores=scores[row], indices=indices[row])


@dataclasses.dataclass
class BankResult:
    """One request's outcome through the service: winners + provenance.
    `degraded` stamps a response served under the degradation ladder —
    the service was past its soft overload watermark, or the wave fell
    back from the fused to the xla kernel. Degraded NEVER means stale:
    winners are current-epoch by the same cache contract as any other
    response; the stamp is latency/arm provenance, not a correctness
    hedge (docs/ROBUSTNESS.md "serving resilience")."""
    topk: TopK
    cached: bool
    degraded: bool = False


class BankService:
    """Request batching + per-(tenant, window) winner caching in front
    of the bank — the serve layer's entry point (`/score`).

    The cache asserts the (tenant, window) contract: a window names one
    immutable event set (a finished day/hour), so its winners are a
    pure function of (tenant, window, tol, max_results) AND the
    tenant's MODEL EPOCH — the epoch at score time is stored with the
    entry, and a hit whose stored epoch trails the tenant's current one
    (feedback applied, model updated/re-saved) is EVICTED and re-scored
    (`bank.cache_epoch_evictions`): a post-feedback request can never
    be served pre-feedback winners. tol and max_results join the key,
    so a repeat of the same window at a different threshold or result
    count is scored fresh, never served the other parameterization's
    winners. A repeat with a DIFFERENT event count is treated as a
    conflict: scored fresh, re-cached, and counted
    (`bank.cache_conflict`) — never served stale."""

    #: Lock discipline, machine-checked by the `locks` analysis pass
    #: (python -m onix.analysis): these attributes are shared across
    #: handler threads and may only be mutated under their declared
    #: lock. `_cache` mutators run under `lock` via submit()'s scoring
    #: section and the serve layer's install path (methods marked
    #: `# lint: holds[lock]`); the admission tallies live under the
    #: separate `_admit_lock` so a shed request never waits on scoring.
    GUARDED_BY = {"_cache": "lock",
                  "_pending": "_admit_lock",
                  "peak_depth": "_admit_lock",
                  "_ewma_wall_s": "_admit_lock"}

    def __init__(self, bank: ModelBank, max_batch_requests: int = 64,
                 cache_size: int = 4096, max_queue_depth: int = 0,
                 request_deadline_s: float = 0.0):
        if max_batch_requests < 1:
            raise ValueError("max_batch_requests must be >= 1")
        if max_queue_depth < 0 or request_deadline_s < 0:
            raise ValueError("max_queue_depth and request_deadline_s "
                             "must be >= 0 (0 = disabled)")
        self.bank = bank
        self.max_batch_requests = max_batch_requests
        self.cache_size = cache_size
        self._cache: OrderedDict[tuple[str, str, float, int],
                                 tuple[int, int, TopK]] = OrderedDict()
        # r16 admission control (docs/ROBUSTNESS.md "serving
        # resilience"): `lock` serializes scoring + filter installs
        # (host-side cache/residency state is shared across handler
        # threads — the serve layer used to hold its own lock here);
        # `max_queue_depth` bounds in-flight + queued submit() calls,
        # beyond which requests SHED (Overloaded → 503 + Retry-After)
        # BEFORE touching any bank state; `request_deadline_s` bounds
        # receipt→scoring-start wall (queue time included).
        self.lock = threading.RLock()
        self.max_queue_depth = max_queue_depth
        self.request_deadline_s = request_deadline_s
        self._admit_lock = threading.Lock()
        self._pending = 0
        self.peak_depth = 0
        # EWMA of recent scoring walls — the Retry-After hint (how long
        # until a queue slot likely frees). Seeded pessimistically low;
        # the first real call corrects it.
        self._ewma_wall_s = 0.05
        # r18: the REAL distribution behind the hint — a log-bucketed
        # histogram of scoring walls (telemetry.Histogram, internally
        # locked). Once it holds enough observations the Retry-After
        # hint uses its median instead of the EWMA point estimate: a
        # bimodal wall (cache hits vs cold waves) no longer averages
        # into a hint that is wrong for both modes. Service-local on
        # purpose — two services in one process must not blend walls.
        self._wall_hist = telemetry.Histogram()

    def _retry_hint_s(self, depth: int) -> float:
        """Seconds until a queue slot likely frees: depth x the median
        scoring wall (the histogram once seeded, the EWMA before)."""
        wall = (self._wall_hist.quantile(0.5) if self._wall_hist.n >= 8
                else self._ewma_wall_s)
        return max(0.1, round(depth * wall, 2))

    # -- admission control + deadline (the submit path) -------------------

    def submit(self, requests: list[ScoreRequest], *, tol: float,
               max_results: int,
               deadline: Deadline | None = None) -> list[BankResult]:
        """The admission-controlled, deadline-bounded serve entry point
        (`/score` and the load harness both come through here).

        Order of refusals, all BEFORE any bank mutation:
          1. depth — `max_queue_depth` submit() calls already in flight
             or queued ⇒ shed (`serve.shed`, Overloaded → HTTP 503 with
             Retry-After). A shed request never touches residency or
             the winner cache (asserted by the overload cell).
          2. deadline — the budget (passed in, or request_deadline_s
             from admission) is checked once scoring WOULD start, i.e.
             after the queue wait; expired ⇒ refused
             (`serve.deadline_expired`, DeadlineExceeded → 503). Once
             scoring starts the request runs to completion — partial
             winner sets are never served.

        Served responses past the soft watermark (depth > half the
        max) or scored through the form-fallback rung are stamped
        `degraded: true` (`serve.degraded`) — an explicit overload
        signal, never stale winners: the epoch-keyed cache contract is
        unchanged on every rung."""
        t_recv = time.perf_counter()
        shed_pending = None
        with self._admit_lock:
            if self.max_queue_depth \
                    and self._pending >= self.max_queue_depth:
                shed_pending = self._pending
            else:
                self._pending += 1
                depth = self._pending
                # Two scopes on purpose: peak_depth is THIS service's
                # high-water (admission_stats / GET /bank/stats — one
                # service per server); the registry gauge is the
                # process-wide max across services (what bench's
                # detail.resilience snapshot carries — a harness
                # running several services reports the worst one).
                self.peak_depth = max(self.peak_depth, depth)
        if shed_pending is not None:
            counters.inc("serve.shed")
            counters.inc("serve.shed_requests", len(requests))
            # Flight-recorder trigger (r18): the ring at shed time IS
            # the overload postmortem — what was in flight, which
            # tenants, which counters moved in the runup. OUTSIDE
            # _admit_lock on purpose: the dump is file I/O over ~1k
            # ring events, and at peak overload every concurrent
            # admission check would otherwise serialize behind it —
            # inflating the served p99 exactly when the r16 bound is
            # being measured.
            telemetry.RECORDER.dump(
                "serve-shed", extra={"pending": shed_pending,
                                     "requests": len(requests)})
            raise Overloaded(
                f"serving queue full ({shed_pending} batches in "
                f"flight, max_queue_depth={self.max_queue_depth})",
                retry_after_s=self._retry_hint_s(shed_pending))
        counters.note_max("serve.queue_depth_peak", depth)
        soft = bool(self.max_queue_depth
                    and depth > max(1, self.max_queue_depth // 2))
        if deadline is None and self.request_deadline_s > 0:
            deadline = Deadline(self.request_deadline_s)
        try:
            with telemetry.TRACER.span("serve.submit",
                                       requests=len(requests),
                                       depth=depth), \
                    self.lock:
                # Clock starts INSIDE the lock: the EWMA must track
                # scoring wall only — folding queue wait in would make
                # the Retry-After hint compound quadratically under
                # sustained contention (wait ≈ depth × ewma ⇒ ewma ≈
                # depth × service ⇒ hint ≈ depth² × service).
                t0 = time.perf_counter()
                # The admission queue wait, as its own span: receipt
                # (submit entry) to scoring start. This is the "why was
                # THIS request slow" number — a fat serve.submit with a
                # fat serve.queue_wait is contention, without one it is
                # scoring cost.
                telemetry.TRACER.observe("serve.queue_wait", t0 - t_recv)
                if deadline is not None and deadline.expired():
                    # counters: resilience.deadline_exceeded is inc'd
                    # by Deadline.check; serve.deadline_expired is the
                    # serve-tier view bench folds into artifacts.
                    counters.inc("serve.deadline_expired")
                    deadline.check("serve request (queued past its "
                                   "deadline budget)")
                fb0 = self.bank.fallback_dispatches
                # Bounded replay for the injected `serve:score` site —
                # it fires at score() entry, before any cache or
                # residency mutation, so the retry is a safe replay.
                results = retry_call(
                    lambda strict: self.score(requests, tol=tol,
                                              max_results=max_results),
                    policy=_SERVE_RETRY, counter_prefix="serve.score",
                    retry_on=faults.InjectedFault)
                fell_back = self.bank.fallback_dispatches > fb0
                wall = time.perf_counter() - t0
            # Histogram first (internally locked): the Retry-After
            # median must see every wall the EWMA sees.
            self._wall_hist.observe(wall)
            # Under _admit_lock: concurrent submits racing this += would
            # lose updates (read-modify-write), skewing the Retry-After
            # hint shed responses derive from it (r17 locks-pass fix).
            with self._admit_lock:
                self._ewma_wall_s += 0.3 * (wall - self._ewma_wall_s)
        finally:
            with self._admit_lock:
                self._pending -= 1
        if soft or fell_back:
            counters.inc("serve.degraded")
            counters.inc("serve.degraded_requests", len(requests))
            results = [dataclasses.replace(r, degraded=True)
                       for r in results]
        counters.inc("serve.served", len(requests))
        return results

    def admission_stats(self) -> dict:
        with self._admit_lock:
            depth = self._pending
        return {"queue_depth": depth,
                "queue_depth_peak": self.peak_depth,
                "max_queue_depth": self.max_queue_depth,
                "request_deadline_s": self.request_deadline_s,
                "shed": counters.get("serve.shed"),
                "shed_requests": counters.get("serve.shed_requests"),
                "deadline_expired": counters.get("serve.deadline_expired"),
                "degraded": counters.get("serve.degraded"),
                "form_fallback": counters.get("serve.form_fallback"),
                "served": counters.get("serve.served")}

    # lint: holds[lock] -- every production call arrives through submit()'s `with self.lock` scoring section; the bank/cache state it touches is serialized there
    def score(self, requests: list[ScoreRequest], *, tol: float,
              max_results: int) -> list[BankResult]:
        with telemetry.TRACER.span("serve.score", requests=len(requests)):
            # Chaos site `serve:score`: entry, pre-mutation (before the
            # disk-epoch probes and cache bookkeeping), so submit()'s
            # bounded retry replays the whole call safely. Inside the
            # span: an injected fault closes it as an error span.
            faults.fire("serve", "score")
            return self._score_locked(requests, tol=tol,
                                      max_results=max_results)

    # lint: holds[lock] -- called only from score(), which submit() serializes (see above)
    def _score_locked(self, requests: list[ScoreRequest], *, tol: float,
                      max_results: int) -> list[BankResult]:
        out: list[BankResult | None] = [None] * len(requests)
        # Out-of-process update probe, once per distinct tenant per
        # call (ModelBank.refresh_from_disk): a re-save by another
        # process moves the epoch BEFORE the hit checks below, so the
        # cache can never serve winners computed under the old file.
        for tenant in {r.tenant for r in requests}:
            self.bank.refresh_from_disk(tenant)
        misses: list[int] = []
        for i, req in enumerate(requests):
            key = (req.tenant, req.window, float(tol), int(max_results)) \
                if req.window is not None else None
            hit = self._cache.get(key) if key is not None else None
            if hit is not None:
                n_cached, epoch_cached, topk = hit
                if epoch_cached != self.bank.epoch(req.tenant):
                    # Scored under an older model epoch: stale by
                    # construction, never serveable.
                    del self._cache[key]
                    counters.inc("bank.cache_epoch_evictions")
                elif n_cached == int(np.asarray(req.doc_ids).size):
                    self._cache.move_to_end(key)
                    counters.inc("bank.cache_hit")
                    out[i] = BankResult(topk, cached=True)
                    continue
                else:
                    counters.inc("bank.cache_conflict")
            if key is not None:     # uncacheable requests don't dilute
                counters.inc("bank.cache_miss")
            misses.append(i)
        for lo in range(0, len(misses), self.max_batch_requests):
            chunk = misses[lo:lo + self.max_batch_requests]
            topks = self.bank.score_batch([requests[i] for i in chunk],
                                          tol=tol, max_results=max_results)
            for i, topk in zip(chunk, topks):
                out[i] = BankResult(topk, cached=False)
                req = requests[i]
                if req.window is not None:
                    # Epoch AFTER scoring: score_batch may have loaded
                    # the tenant (adopting its persisted epoch) — the
                    # entry must carry the epoch its winners were
                    # computed under.
                    self._put(
                        (req.tenant, req.window, float(tol),
                         int(max_results)),
                        (int(np.asarray(req.doc_ids).size),
                         self.bank.epoch(req.tenant), topk))
        return out  # type: ignore[return-value]

    # lint: holds[lock] -- the serve layer's /feedback handler wraps compile+install in `with service.lock` (oa/serve.py), serializing installs against scoring
    def apply_feedback_filter(self, base: str, filt) -> int:
        """The serve layer's one-call feedback install: filter + epoch
        bumps for every KNOWN tenant under `base`
        (bank.set_filter_tree), plus an outright drop of every cache
        entry under the base — an UNLOADED sub-tenant's name is
        unknowable here, so its stale entries cannot be reached
        through epochs (its filter attaches, with a bump, when it next
        loads; but a cached pre-evict entry would hit before any load
        runs). Returns base's new epoch.

        Chaos site `feedback:install` fires at entry — before the
        filter, epochs, or cache are touched — and is absorbed by one
        bounded in-place retry (the install is deterministic in its
        inputs, so the replay installs the identical filter): a fault
        can delay an install by one retry, never lose it or leave a
        half-installed filter live."""
        def _install(strict: bool = True) -> int:
            faults.fire("feedback", "install")
            epoch = self.bank.set_filter_tree(base, filt)
            prefix = base + "/"
            for key in [k for k in self._cache
                        if k[0] == base or k[0].startswith(prefix)]:
                del self._cache[key]
            return epoch
        return retry_call(_install, policy=_SERVE_RETRY,
                          counter_prefix="serve.feedback_install",
                          retry_on=faults.InjectedFault)

    # lint: holds[lock] -- called only from score(), which holds it (see above)
    def _put(self, key, value) -> None:
        self._cache[key] = value
        self._cache.move_to_end(key)
        while len(self._cache) > self.cache_size:
            self._cache.popitem(last=False)

    def cache_stats(self) -> dict:
        return {"entries": len(self._cache),
                "hits": counters.get("bank.cache_hit"),
                "misses": counters.get("bank.cache_miss"),
                "conflicts": counters.get("bank.cache_conflict"),
                "epoch_evictions":
                    counters.get("bank.cache_epoch_evictions")}


# ---------------------------------------------------------------------------
# Refit -> bank epoch propagation (r20, pipelines/fleet.py).
# ---------------------------------------------------------------------------


def publish_refit(bank: ModelBank, tenant: str, theta, phi_wk, *,
                  epoch: int) -> int:
    """Propagate one accepted refit into a live serving bank.

    The fleet supervisor calls this per accepted tenant-day with the
    tenant's LINEAGE epoch (the per-tenant ok-day counter that also
    stamps the persisted model), which rides `add`'s explicit-epoch
    path: the in-memory epoch moves past the previous stamp, the
    tenant's cached winners invalidate, and its device residency
    evicts — for exactly this tenant, no other (the same surgical
    radius the per-tenant quarantine gives the fit side). Returns the
    bank's resulting epoch for the tenant."""
    bank.add(tenant, theta, phi_wk, epoch=int(epoch))
    counters.inc("bank.refit_published")
    return bank.epoch(tenant)
