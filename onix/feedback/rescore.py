"""Filtered selection scans: the noise filter fused into bottom-k.

Each entry point is its unfiltered `onix.models.scoring` twin plus the
`apply_filter` adjustment inside the per-chunk score function — the
SAME `_scan_bottom_k` machinery (chunking, pad masking, running
bottom-k merge, tie rule, -1 sentinel), so a fix to selection logic
still lands in exactly one place and a filtered scan with an empty
filter is bit-identical to the unfiltered scan (filter.py exactness
contract; asserted per run by bench.py's `feedback_rescore`).

Key streams ride the scan as extra chunked columns: the event's word
id (its word key — hi half is an implicit 0) and the packed pair
identity as uint32 (hi, lo) halves (`filter.split_key` of
`filter.pack_pair` keys — (src, dst) docs for flow, (doc, word) for
the single-doc datatypes; 64-bit columns cannot ride the device in
x32). The filter applies BEFORE the tol screen: a boosted
(confirmed-threat) event whose scaled score clears tol stays in the
winner set; a suppressed event never reaches the merge.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from onix.feedback.filter import FilterTables, apply_filter
from onix.models.scoring import TopK, _scan_bottom_k, _subscan_scores


def _word_halves(wc):
    """Word ids → (hi, lo) uint32 key halves (word keys are < 2^32, so
    hi is constant 0)."""
    lo = wc.astype(jnp.uint32)
    return jnp.zeros_like(lo), lo


@functools.partial(jax.jit,
                   static_argnames=("max_results", "chunk", "merge_buffer"))
def top_suspicious_filtered(
    theta: jax.Array,
    phi_wk: jax.Array,
    doc_ids: jax.Array,       # int32 [N]
    word_ids: jax.Array,      # int32 [N]
    mask: jax.Array,          # float32 [N] 0.0 for padding
    pair_hi: jax.Array,       # uint32 [N] packed-pair high half
    pair_lo: jax.Array,       # uint32 [N] packed-pair low half
    filt: FilterTables,
    *,
    tol: float,
    max_results: int,
    chunk: int = 1 << 20,
    merge_buffer: int | None = None,
) -> TopK:
    """`top_suspicious` with the fused noise-filter adjustment. The
    word key is the event's own word id; the pair halves carry
    whatever pair identity the caller filters on."""

    def score_chunk(dc, wc, ph, pl, mc):
        s = _subscan_scores(theta, phi_wk, dc, wc)
        s = apply_filter(s, _word_halves(wc), (ph, pl), filt)
        return jnp.where((mc > 0) & (s < tol), s, jnp.inf)

    return _scan_bottom_k((doc_ids, word_ids, pair_hi, pair_lo, mask),
                          doc_ids.shape[0], score_chunk,
                          max_results=max_results, chunk=chunk,
                          merge_buffer=merge_buffer)


@functools.partial(jax.jit,
                   static_argnames=("max_results", "chunk", "merge_buffer"))
def table_bottom_k_filtered(
    table_flat: jax.Array,   # float32 [D*V] from score_table().ravel()
    idx: jax.Array,          # int32 [N] flat index d*V + w per event
    word_ids: jax.Array,     # int32/uint32 [N] the event's word id
    pair_hi: jax.Array,      # uint32 [N]
    pair_lo: jax.Array,      # uint32 [N]
    filt: FilterTables,
    *,
    tol: float,
    max_results: int,
    chunk: int = 1 << 21,
    merge_buffer: int | None = None,
) -> TopK:
    """`table_bottom_k` (dns/proxy fused path) with the filter fused
    into the same scan."""

    def score_chunk(ii, wc, ph, pl):
        s = table_flat[ii]
        s = apply_filter(s, _word_halves(wc), (ph, pl), filt)
        return jnp.where(s < tol, s, jnp.inf)

    return _scan_bottom_k((idx, word_ids, pair_hi, pair_lo),
                          idx.shape[0], score_chunk,
                          max_results=max_results, chunk=chunk,
                          merge_buffer=merge_buffer)


@functools.partial(jax.jit,
                   static_argnames=("max_results", "chunk", "merge_buffer"))
def table_pair_bottom_k_filtered(
    table_flat: jax.Array,   # float32 [D*V] from score_table().ravel()
    idx_src: jax.Array,      # int32 [N] flat index d_src*V + w per event
    idx_dst: jax.Array,      # int32 [N] flat index d_dst*V + w per event
    word_ids: jax.Array,     # int32/uint32 [N] the event's word id
    pair_hi: jax.Array,      # uint32 [N] src-doc half of the pair key
    pair_lo: jax.Array,      # uint32 [N] dst-doc half
    filt: FilterTables,
    *,
    tol: float,
    max_results: int,
    chunk: int = 1 << 21,
    merge_buffer: int | None = None,
) -> TopK:
    """`table_pair_bottom_k` (the flow 10⁸⁺-event path) with the
    filter fused into the same scan — the (src, dst)-pair suppression
    of PAPER.md §L5's noise filter, applied after the pair-min and
    before the tol screen."""

    def score_chunk(si, di, wc, ph, pl):
        s = jnp.minimum(table_flat[si], table_flat[di])
        s = apply_filter(s, _word_halves(wc), (ph, pl), filt)
        return jnp.where(s < tol, s, jnp.inf)

    return _scan_bottom_k((idx_src, idx_dst, word_ids, pair_hi, pair_lo),
                          idx_src.shape[0], score_chunk,
                          max_results=max_results, chunk=chunk,
                          merge_buffer=merge_buffer)


# ---------------------------------------------------------------------------
# Serve-gated dispatchers (r15): each is its filtered scan above plus
# the one-kernel fused arm behind `pallas_serve.select_serve_form`
# (serving.serve_form / ONIX_SERVE_FORM; "auto" resolves to the XLA
# scan on every backend until a measured crossover table entry lands).
# Both arms are bit-identical — winners, scores, tie order — so the
# dispatch is pure performance (tests/test_pallas_serve.py).
# ---------------------------------------------------------------------------


def top_suspicious_filtered_fast(theta, phi_wk, doc_ids, word_ids, mask,
                                 pair_hi, pair_lo, filt: FilterTables, *,
                                 tol: float, max_results: int,
                                 serve_form: str = "auto") -> TopK:
    """`top_suspicious_filtered` behind the serve gate. Chained tables
    (theta.ndim == 3) always take the XLA scan — the fused arm covers
    single-estimate tables only."""
    from onix.models import pallas_serve
    form = pallas_serve.select_serve_form(serve_form, doc_ids.shape[0])
    if form == "fused" and jnp.asarray(theta).ndim == 2:
        return pallas_serve.fused_top_suspicious(
            theta, phi_wk, doc_ids, word_ids, mask, pair_hi, pair_lo,
            filt, tol=tol, max_results=max_results)
    return top_suspicious_filtered(theta, phi_wk, doc_ids, word_ids,
                                   mask, pair_hi, pair_lo, filt,
                                   tol=tol, max_results=max_results)


def table_bottom_k_filtered_fast(table_flat, idx, word_ids, pair_hi,
                                 pair_lo, filt: FilterTables, *,
                                 tol: float, max_results: int,
                                 serve_form: str = "auto") -> TopK:
    """`table_bottom_k_filtered` behind the serve gate."""
    from onix.models import pallas_serve
    form = pallas_serve.select_serve_form(serve_form, idx.shape[0])
    if form == "fused":
        return pallas_serve.fused_table_bottom_k(
            table_flat, idx, word_ids, pair_hi, pair_lo, filt,
            tol=tol, max_results=max_results)
    return table_bottom_k_filtered(table_flat, idx, word_ids, pair_hi,
                                   pair_lo, filt, tol=tol,
                                   max_results=max_results)


def table_pair_bottom_k_filtered_fast(table_flat, idx_src, idx_dst,
                                      word_ids, pair_hi, pair_lo,
                                      filt: FilterTables, *, tol: float,
                                      max_results: int,
                                      serve_form: str = "auto") -> TopK:
    """`table_pair_bottom_k_filtered` (the judged filtered flow path)
    behind the serve gate."""
    from onix.models import pallas_serve
    form = pallas_serve.select_serve_form(serve_form, idx_src.shape[0])
    if form == "fused":
        return pallas_serve.fused_table_pair_bottom_k(
            table_flat, idx_src, idx_dst, word_ids, pair_hi, pair_lo,
            filt, tol=tol, max_results=max_results)
    return table_pair_bottom_k_filtered(table_flat, idx_src, idx_dst,
                                        word_ids, pair_hi, pair_lo,
                                        filt, tol=tol,
                                        max_results=max_results)
