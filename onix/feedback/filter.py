"""The compiled noise filter: feedback verdicts as device-array tables.

The filter is two families of sorted uint64 key tables — WORD keys
(a word/bucket id alone) and PAIR keys (two 32-bit identities packed
into one uint64: (src, dst) docs for flow, (client, bucket) for
dns/proxy, (doc, word) for the serving bank) — each split into a
SUPPRESS set (benign verdicts: the event must stop surfacing) and a
BOOST set (confirmed threats: the event must keep surfacing).
Application is a fused post-score adjustment:

    s  →  boost member ? s * boost_scale : s      (scale <= 1)
    s  →  suppress member ? +inf : s

run INSIDE the chunked bottom-k scan / bank kernel before the tol
screen, so a suppressed winner never reaches the merge and a boosted
event survives the threshold.

Device rendering: the repo runs JAX in x32 (conftest pins
jax_enable_x64=False — a 64-bit device array would silently downcast),
so each uint64 table ships as TWO sorted uint32 half columns (hi, lo)
and membership is an exact branchless lexicographic binary search —
log2(F) unrolled steps of (gather, compare, select) per key family per
chunk, against tables that are typically tens of entries.

Exactness contract: every table is padded with `SENTINEL`
(0xFFFF...F — the all-ones key, reserved: no real (identity, identity)
pair is all-ones) to a pow2 length, so an EMPTY filter is an
all-sentinel table whose membership mask is constant False, and
`jnp.where(False, ·, s)` returns s unchanged — the filtered scan with
a filter of zero entries is bit-identical to the unfiltered scan
(tested, and asserted per run by bench.py's `feedback_rescore`
component).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import numpy as np

# The reserved all-ones key pads every table: above every real key in
# unsigned order, and no real identity pair packs to it (it would need
# BOTH halves to be 0xFFFFFFFF).
SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)
# Pow2 floor for device filter tables: bounds the compiled-shape ladder
# (a one-entry filter and an empty one share a shape class).
FILTER_FLOOR = 8

BENIGN_LABEL = 3            # the reference severity scale: 1/2 threat


def pack_pair(hi, lo) -> np.ndarray:
    """Two 32-bit identities → one uint64 key (hi << 32 | lo). Used
    for (src, dst) flow doc pairs, (client, bucket) dns/proxy pairs,
    and (doc, word) serving-bank pairs alike."""
    return ((np.asarray(hi).astype(np.uint64) << np.uint64(32))
            | (np.asarray(lo).astype(np.uint64)
               & np.uint64(0xFFFFFFFF)))


def split_key(keys) -> tuple[np.ndarray, np.ndarray]:
    """uint64 keys → (hi, lo) uint32 halves — the x32-safe device
    rendering of a 64-bit key stream."""
    k = np.asarray(keys, np.uint64)
    return ((k >> np.uint64(32)).astype(np.uint32),
            (k & np.uint64(0xFFFFFFFF)).astype(np.uint32))


def _sorted_unique(keys) -> np.ndarray:
    if keys is None:
        return np.empty(0, np.uint64)
    return np.unique(np.asarray(keys, np.uint64))


def _pad_sorted(keys: np.ndarray, floor: int = FILTER_FLOOR) -> np.ndarray:
    """Sorted keys → sentinel-padded pow2 uint64 array (>= floor).
    All-sentinel when empty — membership against it is constant
    False."""
    n = max(int(keys.shape[0]), 1)
    size = floor
    while size < n:
        size <<= 1
    out = np.full(size, SENTINEL, np.uint64)
    out[:keys.shape[0]] = keys
    return out


@dataclasses.dataclass(frozen=True)
class HostFilter:
    """Host-side compiled filter: sorted UNPADDED uint64 key arrays.
    Immutable; `merged` composes incremental feedback applications."""

    word_suppress: np.ndarray
    word_boost: np.ndarray
    pair_suppress: np.ndarray
    pair_boost: np.ndarray
    boost_scale: float = 0.25

    @classmethod
    def empty(cls, boost_scale: float = 0.25) -> "HostFilter":
        e = np.empty(0, np.uint64)
        return cls(e, e, e, e, boost_scale)

    @property
    def n_entries(self) -> int:
        return (len(self.word_suppress) + len(self.word_boost)
                + len(self.pair_suppress) + len(self.pair_boost))

    @property
    def empty_filter(self) -> bool:
        return self.n_entries == 0

    def merged(self, *, word_suppress=None, word_boost=None,
               pair_suppress=None, pair_boost=None) -> "HostFilter":
        """New filter with the given keys unioned in. A key present in
        both a suppress set and a boost set keeps the NEWEST verdict:
        keys added to suppress are removed from boost and vice versa
        (re-labeling must never leave an event both suppressed and
        boosted — suppression would silently win). A key given in BOTH
        new sets of one call (two alert rows of the same pair, labeled
        benign AND threat together) has no newest verdict — the
        conflicting evidence cancels and the key keeps its PRIOR
        state, rather than being silently dropped from both sets."""
        ws_new = _sorted_unique(word_suppress)
        wb_new = _sorted_unique(word_boost)
        conflict = np.intersect1d(ws_new, wb_new)
        ws_new = np.setdiff1d(ws_new, conflict)
        wb_new = np.setdiff1d(wb_new, conflict)
        ps_new = _sorted_unique(pair_suppress)
        pb_new = _sorted_unique(pair_boost)
        conflict = np.intersect1d(ps_new, pb_new)
        ps_new = np.setdiff1d(ps_new, conflict)
        pb_new = np.setdiff1d(pb_new, conflict)
        ws = np.union1d(self.word_suppress, ws_new)
        wb = np.union1d(self.word_boost, wb_new)
        ps = np.union1d(self.pair_suppress, ps_new)
        pb = np.union1d(self.pair_boost, pb_new)
        wb = np.setdiff1d(wb, ws_new)
        ws = np.setdiff1d(ws, wb_new)
        pb = np.setdiff1d(pb, ps_new)
        ps = np.setdiff1d(ps, pb_new)
        return HostFilter(ws.astype(np.uint64), wb.astype(np.uint64),
                          ps.astype(np.uint64), pb.astype(np.uint64),
                          self.boost_scale)

    # -- host-side application (streaming winner selection) ---------------

    @staticmethod
    def member(keys: np.ndarray, table: np.ndarray) -> np.ndarray:
        """bool [N] membership of uint64 keys in a sorted unpadded
        table — the NumPy twin of the device `_member` (same
        searchsorted semantics, no padding needed host-side)."""
        keys = np.asarray(keys, np.uint64)
        if table.shape[0] == 0 or keys.shape[0] == 0:
            return np.zeros(keys.shape[0], bool)
        idx = np.searchsorted(table, keys)
        idx = np.minimum(idx, table.shape[0] - 1)
        return table[idx] == keys

    def apply_word(self, scores: np.ndarray,
                   word_keys: np.ndarray) -> np.ndarray:
        """Word-level adjustment of token scores (host arrays)."""
        s = scores
        boo = self.member(word_keys, self.word_boost)
        if boo.any():
            s = np.where(boo, s * self.boost_scale, s)
        sup = self.member(word_keys, self.word_suppress)
        if sup.any():
            s = np.where(sup, np.inf, s)
        return s

    def apply_pair(self, scores: np.ndarray,
                   pair_keys: np.ndarray) -> np.ndarray:
        """Pair-level adjustment of event scores (host arrays)."""
        s = scores
        boo = self.member(pair_keys, self.pair_boost)
        if boo.any():
            s = np.where(boo, s * self.boost_scale, s)
        sup = self.member(pair_keys, self.pair_suppress)
        if sup.any():
            s = np.where(sup, np.inf, s)
        return s

    # -- device rendering --------------------------------------------------

    def tables(self, floor: int = FILTER_FLOOR) -> "FilterTables":
        """Sentinel-padded pow2 device tables, each a (hi, lo) uint32
        half pair (x32-safe)."""
        import jax.numpy as jnp

        def dev(keys):
            hi, lo = split_key(_pad_sorted(keys, floor))
            return jnp.asarray(hi), jnp.asarray(lo)

        return FilterTables(
            word_suppress=dev(self.word_suppress),
            word_boost=dev(self.word_boost),
            pair_suppress=dev(self.pair_suppress),
            pair_boost=dev(self.pair_boost),
            boost_scale=jnp.float32(self.boost_scale))


class FilterTables(NamedTuple):
    """Device rendering of a HostFilter: per family a (hi, lo) pair of
    sorted, SENTINEL-padded pow2 uint32 arrays (a pytree — passes
    straight through jit; the pow2 pad bounds recompiles to
    O(log max_entries) shape classes)."""

    word_suppress: tuple        # (uint32 [Fw], uint32 [Fw])
    word_boost: tuple           # (uint32 [Fb], uint32 [Fb])
    pair_suppress: tuple        # (uint32 [Fp], uint32 [Fp])
    pair_boost: tuple           # (uint32 [Fq], uint32 [Fq])
    boost_scale: object         # float32 [] — traced, no retrace on change


def empty_tables(floor: int = FILTER_FLOOR) -> FilterTables:
    return HostFilter.empty().tables(floor)


def _member(khi, klo, table):
    """bool [N]: (hi, lo) keys present in the sorted sentinel-padded
    (hi, lo) table. Exact branchless lexicographic lower-bound over the
    pow2 table — log2(F) unrolled (gather, compare, select) steps; the
    all-sentinel (empty) table gives constant False for any real key."""
    import jax.numpy as jnp
    hi_t, lo_t = table
    f = int(hi_t.shape[0])
    pos = jnp.zeros(khi.shape, jnp.int32)
    step = f
    while step > 1:
        step >>= 1
        probe = pos + (step - 1)
        h = hi_t[probe]
        l_ = lo_t[probe]
        less = (h < khi) | ((h == khi) & (l_ < klo))
        pos = jnp.where(less, pos + step, pos)
    return (hi_t[pos] == khi) & (lo_t[pos] == klo)


def apply_filter(scores, word_keys, pair_keys, filt: FilterTables):
    """The fused post-score adjustment (device): boost members scale by
    boost_scale, suppress members go to +inf. `word_keys` / `pair_keys`
    are (hi, lo) uint32 pairs (split_key). Runs BEFORE the tol screen
    so boosted events survive the threshold and suppressed ones never
    reach the bottom-k merge. With empty tables both `where`s select
    the untouched branch elementwise — bit-identical scores."""
    import jax.numpy as jnp
    boo = _member(*word_keys, filt.word_boost) \
        | _member(*pair_keys, filt.pair_boost)
    s = jnp.where(boo, scores * filt.boost_scale, scores)
    sup = _member(*word_keys, filt.word_suppress) \
        | _member(*pair_keys, filt.pair_suppress)
    return jnp.where(sup, jnp.inf, s)


# ---------------------------------------------------------------------------
# Compiling the feedback log (oa/feedback.py CSVs) into a filter.
#
# The CSV's (ip, word) columns are display strings — meaningful to the
# analyst, not to a scorer keyed by integer ids. Rows that carry the
# OPTIONAL integer columns `word_id` / `doc_id` (the ids a /score
# client used, echoed back when labeling) compile directly: word_id
# alone → a word key; doc_id + word_id → a (doc, word) pair key. The
# streaming scorer compiles its own filter from raw alert rows instead
# (StreamingScorer.apply_feedback re-derives buckets through the same
# frozen-edge word path), so string-only CSVs still close the loop
# there.
# ---------------------------------------------------------------------------


def compile_feedback(df, boost_scale: float = 0.25) -> HostFilter:
    """Feedback rows (label + optional doc_id/word_id ints) → filter.
    Benign labels (3) suppress; threat labels (1/2) boost. Rows with
    no usable integer ids are skipped (they still feed the ×DUPFACTOR
    corpus path and the streaming apply_feedback path)."""
    import pandas as pd

    if df is None or len(df) == 0:
        return HostFilter.empty(boost_scale)
    label = pd.to_numeric(df.get("label"), errors="coerce")
    wid = pd.to_numeric(df["word_id"], errors="coerce") \
        if "word_id" in df.columns else None
    did = pd.to_numeric(df["doc_id"], errors="coerce") \
        if "doc_id" in df.columns else None
    if wid is None:
        return HostFilter.empty(boost_scale)
    wid_np = wid.to_numpy(np.float64)
    did_np = (did.to_numpy(np.float64) if did is not None
              else np.full(len(df), np.nan))
    lab = label.to_numpy(np.float64)
    valid_w = np.isfinite(wid_np) & np.isfinite(lab) & (wid_np >= 0)
    benign = lab == BENIGN_LABEL
    has_pair = valid_w & np.isfinite(did_np) & (did_np >= 0)
    word_only = valid_w & ~has_pair
    pair_keys = pack_pair(did_np[has_pair].astype(np.uint32),
                          wid_np[has_pair].astype(np.uint32))
    word_keys = wid_np[word_only].astype(np.uint64)
    return HostFilter.empty(boost_scale).merged(
        word_suppress=word_keys[benign[word_only]],
        word_boost=word_keys[~benign[word_only]],
        pair_suppress=pair_keys[benign[has_pair]],
        pair_boost=pair_keys[~benign[has_pair]])


def filter_from_csv(path, boost_scale: float = 0.25) -> HostFilter:
    """Compile a feedback CSV (oa/feedback.py layout) into a filter;
    missing file → empty filter."""
    import pathlib

    import pandas as pd

    p = pathlib.Path(path)
    if not p.exists():
        return HostFilter.empty(boost_scale)
    return compile_feedback(pd.read_csv(p), boost_scale)
