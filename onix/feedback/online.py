"""Incremental online model updates from analyst feedback.

The reference's only learning path is the next DAY's cold refit with
the feedback rows duplicated ×DUPFACTOR into the corpus (SURVEY.md
§3.3). The updater here closes the loop WITHOUT a refit, in the spirit
of the streaming-Gibbs/SCVB0 update family (arxiv 1601.01142 /
1305.2452): the feedback rows become ONE weighted minibatch replayed
through the existing `lda_svi.svi_step` machinery — the same weighted-
mask path the deduped streaming E-step already rides — so a weight-w
dismissed row updates λ exactly as w identical observed tokens would.

Direction of the nudge: scoring is p(word | doc) with LOW = suspicious,
so a DISMISSED (benign) row must gain probability — its tokens enter
the minibatch at `feedback.dismiss_weight` (the ×DUPFACTOR analog) and
the natural-gradient λ-step plus the weighted E-step raise
p(word | doc) until the traffic stops scoring suspicious. CONFIRMED
threats must NOT gain probability (that would teach the model the
attack is common — the exact failure `run.load_feedback` guards
against): they default to weight 0 and act through the boost filter
instead (`feedback.confirm_weight` exists for experiments).

The fitted batch model (θ, φ) has no λ, so the updater lifts φ into a
pseudo-count λ0 = η + prior_strength·φ — the nudge then moves a
posterior carrying `prior_strength` tokens of prior mass, not a fresh
model — and blends the updated document rows as
θ'_d ∝ theta_strength·θ_d + (γ_d − α). Persisted models bump their
`model_epoch` (checkpoint.save_model), which the serving bank's
winner cache keys on: post-update requests can never be served
pre-update winners.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

from onix.config import FeedbackConfig, LDAConfig
from onix.feedback.filter import BENIGN_LABEL


@dataclasses.dataclass
class NudgeResult:
    theta: np.ndarray
    phi_wk: np.ndarray
    stats: dict


class OnlineUpdater:
    """Feedback-weighted minibatch updates for a fitted (θ, φ) model."""

    def __init__(self, lda: LDAConfig, fb: FeedbackConfig):
        lda.validate()
        fb.validate()
        self.lda = lda
        self.fb = fb

    def _weights(self, labels: np.ndarray) -> np.ndarray:
        lab = np.asarray(labels)
        return np.where(lab == BENIGN_LABEL,
                        np.float32(self.fb.dismiss_weight),
                        np.float32(self.fb.confirm_weight))

    def nudge(self, theta: np.ndarray, phi_wk: np.ndarray,
              doc_ids: np.ndarray, word_ids: np.ndarray,
              labels: np.ndarray) -> NudgeResult:
        """One feedback application: (θ, φ) nudged by the weighted
        minibatch, `online_steps` svi_step replays. Zero-weight rows
        (default: every confirmation) drop out; an all-zero batch
        returns the model unchanged."""
        import jax.numpy as jnp

        from onix.models.lda_svi import (SVIState, make_minibatch,
                                         phi_estimate, svi_step)
        from onix.models.scoring import score_events

        theta = np.asarray(theta, np.float32)
        phi_wk = np.asarray(phi_wk, np.float32)
        if theta.ndim != 2:
            raise ValueError("online updates need a single-estimate "
                             "theta [D,K]; combine chains upstream")
        d = np.asarray(doc_ids, np.int32)
        w = np.asarray(word_ids, np.int32)
        lab = np.asarray(labels)
        if not (d.shape == w.shape == lab.shape and d.ndim == 1):
            raise ValueError("doc_ids/word_ids/labels must be equal-"
                             "length 1-d arrays")
        if d.size and (d.min() < 0 or d.max() >= theta.shape[0]
                       or w.min() < 0 or w.max() >= phi_wk.shape[0]):
            raise ValueError("feedback ids out of range for the model")
        weights = self._weights(lab)
        keep = weights > 0
        stats = {"n_rows": int(d.size), "n_weighted": int(keep.sum()),
                 "online_steps": 0}
        if not keep.any():
            return NudgeResult(theta, phi_wk, stats)
        d, w, weights = d[keep], w[keep], weights[keep]

        k = theta.shape[1]
        alpha = self.lda.alpha
        # Column-normalize before the lift: fitted phi columns are
        # p(word|topic) and already sum to 1, but the lift must put
        # exactly prior_strength pseudo-tokens per topic regardless of
        # how the caller's tables were scaled.
        phi_norm = phi_wk / np.maximum(phi_wk.sum(axis=0, keepdims=True),
                                       1e-30)
        lam0 = self.lda.eta + self.fb.prior_strength * phi_norm
        state = SVIState(lam=jnp.asarray(lam0),
                         step=jnp.zeros((), jnp.int32))
        batch = make_minibatch(d, w, weights=weights)
        # Warm-start each doc's fixed point from its fitted mixture at
        # theta_strength pseudo-tokens, so the E-step moves a posterior,
        # not a cold prior.
        dm = np.asarray(batch.doc_map)
        real = dm >= 0
        g0 = np.full((batch.n_docs, k), alpha + 1.0, np.float32)
        g0[real] = alpha + self.fb.theta_strength * theta[dm[real]]
        step = functools.partial(
            svi_step, alpha=alpha, eta=self.lda.eta,
            tau0=self.lda.svi_tau0, kappa=self.lda.svi_kappa,
            local_iters=self.lda.svi_local_iters,
            meanchange_tol=self.lda.svi_meanchange_tol,
            warm_iters=0, batch_docs=batch.n_docs)
        before = np.asarray(score_events(jnp.asarray(theta),
                                         jnp.asarray(phi_wk),
                                         jnp.asarray(d), jnp.asarray(w)))
        gamma = jnp.asarray(g0)
        # corpus_docs = the batch's OWN doc count: svi_step scales a
        # minibatch by corpus_docs/batch_docs to extrapolate it to the
        # corpus, but a feedback batch represents only itself — the
        # full-corpus scale would let a handful of weight-1000 rows
        # grab most of each topic column and DEFLATE every other
        # word's φ through the normalization (measured: unrelated pair
        # scores fell ~16x), breaking the zero-lag-on-everything-else
        # contract. The verdicts' mass is dismiss_weight alone.
        n_real_docs = float((dm >= 0).sum())
        for _ in range(self.fb.online_steps):
            state, gamma = step(state, batch, n_real_docs, gamma)
            stats["online_steps"] += 1
        phi2 = np.asarray(phi_estimate(state))
        gm = np.asarray(gamma)
        theta2 = theta.copy()
        rows = (self.fb.theta_strength * theta[dm[real]]
                + np.maximum(gm[real] - alpha, 0.0))
        theta2[dm[real]] = rows / rows.sum(axis=1, keepdims=True)
        after = np.asarray(score_events(jnp.asarray(theta2),
                                        jnp.asarray(phi2),
                                        jnp.asarray(d), jnp.asarray(w)))
        stats["mean_score_before"] = float(before.mean())
        stats["mean_score_after"] = float(after.mean())
        return NudgeResult(theta2, phi2, stats)

    def nudge_and_save(self, models_dir, name: str,
                       doc_ids, word_ids, labels) -> NudgeResult:
        """Load a persisted model, nudge it, and re-save it under a
        BUMPED model epoch (checkpoint.save_model) — the durable side
        of the loop: a restarted server banks the updated tables, and
        the epoch-keyed winner cache can never serve pre-feedback
        winners for the new epoch."""
        from onix.checkpoint import load_model, save_model

        m = load_model(models_dir, name)
        if m is None:
            raise FileNotFoundError(f"no model {name!r} under "
                                    f"{models_dir}")
        res = self.nudge(m.arrays["theta"], m.arrays["phi_wk"],
                         doc_ids, word_ids, labels)
        epoch = int(m.meta.get("model_epoch", 0)) + 1
        save_model(models_dir, name, res.theta, res.phi_wk,
                   meta={k: v for k, v in m.meta.items()
                         if k in ("engine", "config_hash")},
                   epoch=epoch)
        res.stats["model_epoch"] = epoch
        return res
