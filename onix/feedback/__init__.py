"""Analyst feedback loop — verdicts become model behavior (r13).

In the reference the OA layer's whole point is this loop: analysts
label suspicious connects, a noise filter suppresses dismissed
traffic, and the next run's model learns from the labels
(PAPER.md §L5 "analyst UI, heuristics, noise filter, feedback
capture"; reference README.md:48 ×DUPFACTOR). `oa/feedback.py` is the
WRITE side (labels → CSV); this package is the READ side, on two
timescales:

* `filter` / `rescore` — **immediate rescoring**: the feedback log
  compiles into a per-(datatype, date, tenant) noise filter —
  suppressed/boosted word ids and pair keys as device arrays —
  applied as a fused post-score adjustment inside the existing
  bottom-k scan machinery (`scoring._scan_bottom_k`), the model-bank
  batched kernels, and the streaming winner selection. Dismissed
  winners drop out of `/score` and the streaming alert set on the
  very next request, without refitting.
* `online` — **incremental model updates**: feedback-weighted
  minibatches replayed through the existing SVI machinery
  (`lda_svi.svi_step` — the same weighted-mask path the deduped
  streaming E-step rides) nudge λ/φ without a cold refit, persisted
  via `checkpoint.save_model` under a bumped model epoch.
"""

from onix.feedback.filter import (FilterTables, HostFilter,  # noqa: F401
                                  apply_filter, compile_feedback,
                                  filter_from_csv, pack_pair)
from onix.feedback.online import OnlineUpdater  # noqa: F401
