"""ctypes bridge to the C++ reference engine `onix-lda-ref`.

The reference's oni-lda-c binary (reference README.md:84,125) is not in
the mount, so onix carries its own native stand-in (SURVEY.md §2.4.1):
a C++ collapsed-Gibbs + variational-EM engine on the same corpus. This
module builds it on demand (g++, cached by source mtime) and exposes the
two algorithms with a NumPy surface, plus the top-k overlap metric the
judge scores (BASELINE.json `metric`: "top-1k suspicious-connect overlap
vs lda-c").
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess

import numpy as np

from onix.corpus import SparseCounts

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native" / "lda_ref"
_LIB_PATH = _NATIVE_DIR / "build" / "libonix_lda_ref.so"
_BIN_PATH = _NATIVE_DIR / "build" / "lda_ref"

_lib = None


class OracleUnavailable(RuntimeError):
    pass


def _build() -> None:
    try:
        subprocess.run(["make", "-C", str(_NATIVE_DIR)], check=True,
                       capture_output=True, text=True)
    except (OSError, subprocess.CalledProcessError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        raise OracleUnavailable(f"cannot build onix-lda-ref: {detail}") from e


def _stale() -> bool:
    if not _LIB_PATH.exists() or not _BIN_PATH.exists():
        return True
    built = min(_LIB_PATH.stat().st_mtime, _BIN_PATH.stat().st_mtime)
    return any(built < (_NATIVE_DIR / f).stat().st_mtime
               for f in ("lda_ref.cpp", "Makefile"))


def load_library() -> ctypes.CDLL:
    """Build (if needed) and load the shared library, declaring signatures."""
    global _lib
    if _lib is not None:
        return _lib
    if _stale():
        _build()
    lib = ctypes.CDLL(str(_LIB_PATH))
    i32p = ctypes.POINTER(ctypes.c_int32)
    f32p = ctypes.POINTER(ctypes.c_float)
    f64p = ctypes.POINTER(ctypes.c_double)
    lib.onix_lda_gibbs.restype = ctypes.c_int
    lib.onix_lda_gibbs.argtypes = [
        i32p, i32p, i32p, ctypes.c_int64,                    # triples
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,      # D, V, K
        ctypes.c_double, ctypes.c_double,                    # alpha, eta
        ctypes.c_int32, ctypes.c_int32,                      # sweeps, burn-in
        ctypes.c_uint64, ctypes.c_int32,                     # seed, threads
        f32p, f32p, f64p,                                    # theta, phi, ll
    ]
    lib.onix_lda_vem.restype = ctypes.c_int
    lib.onix_lda_vem.argtypes = [
        i32p, i32p, i32p, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_double, ctypes.c_double,
        ctypes.c_int32, ctypes.c_double,                     # em iters/conv
        ctypes.c_int32, ctypes.c_double,                     # var iters/conv
        ctypes.c_uint64, ctypes.c_int32,
        f32p, f32p, f64p,
    ]
    _lib = lib
    return lib


def _as_ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def gibbs(counts: SparseCounts, *, n_topics: int, alpha: float, eta: float,
          n_sweeps: int = 100, burn_in: int | None = None, seed: int = 0,
          n_threads: int = 1) -> dict:
    """Run the C++ collapsed-Gibbs engine. Exact when n_threads == 1;
    AD-LDA (per-sweep count merge, ≙ the reference's MPI reduce) otherwise.

    Returns {"theta" [D,K], "phi" [K,V], "ll" [n_sweeps]}.
    """
    lib = load_library()
    burn_in = n_sweeps // 2 if burn_in is None else burn_in
    d = np.ascontiguousarray(counts.doc_ids, np.int32)
    w = np.ascontiguousarray(counts.word_ids, np.int32)
    c = np.ascontiguousarray(counts.counts, np.int32)
    theta = np.empty((counts.n_docs, n_topics), np.float32)
    phi = np.empty((n_topics, counts.n_vocab), np.float32)
    ll = np.empty(n_sweeps, np.float64)
    rc = lib.onix_lda_gibbs(
        _as_ptr(d, ctypes.c_int32), _as_ptr(w, ctypes.c_int32),
        _as_ptr(c, ctypes.c_int32), counts.nnz,
        counts.n_docs, counts.n_vocab, n_topics, alpha, eta,
        n_sweeps, burn_in, seed, n_threads,
        _as_ptr(theta, ctypes.c_float), _as_ptr(phi, ctypes.c_float),
        _as_ptr(ll, ctypes.c_double))
    if rc != 0:
        raise RuntimeError(f"onix_lda_gibbs failed with rc={rc}")
    return {"theta": theta, "phi": phi, "ll": ll}


def vem(counts: SparseCounts, *, n_topics: int, alpha: float, eta: float,
        em_max_iter: int = 100, em_conv: float = 1e-5, var_max_iter: int = 30,
        var_conv: float = 1e-6, seed: int = 0, n_threads: int = 1) -> dict:
    """Run the C++ variational-EM engine (Blei lda-c lineage).

    Returns {"theta" [D,K], "phi" [K,V], "ll" [em_max_iter]}.
    """
    lib = load_library()
    d = np.ascontiguousarray(counts.doc_ids, np.int32)
    w = np.ascontiguousarray(counts.word_ids, np.int32)
    c = np.ascontiguousarray(counts.counts, np.int32)
    theta = np.empty((counts.n_docs, n_topics), np.float32)
    phi = np.empty((n_topics, counts.n_vocab), np.float32)
    ll = np.empty(em_max_iter, np.float64)
    rc = lib.onix_lda_vem(
        _as_ptr(d, ctypes.c_int32), _as_ptr(w, ctypes.c_int32),
        _as_ptr(c, ctypes.c_int32), counts.nnz,
        counts.n_docs, counts.n_vocab, n_topics, alpha, eta,
        em_max_iter, em_conv, var_max_iter, var_conv, seed, n_threads,
        _as_ptr(theta, ctypes.c_float), _as_ptr(phi, ctypes.c_float),
        _as_ptr(ll, ctypes.c_double))
    if rc != 0:
        raise RuntimeError(f"onix_lda_vem failed with rc={rc}")
    return {"theta": theta, "phi": phi, "ll": ll}


def gibbs_ensemble_scores(counts: SparseCounts, doc_ids: np.ndarray,
                          word_ids: np.ndarray, *, n_topics: int,
                          alpha: float, eta: float, n_sweeps: int = 300,
                          n_runs: int = 8, seed: int = 0,
                          n_threads: int = 1) -> np.ndarray:
    """Geometric-mean event scores over `n_runs` independent Gibbs runs.

    Event scores are invariant to topic relabeling, so averaging them
    across restarts is a legitimate posterior-predictive estimate; the
    geometric mean is the rank-stable choice for the suspicious tail
    (an event must be low under EVERY run to stay in the bottom-k).
    This is the oracle side of the judged top-1k overlap harness — the
    stand-in for "lda-c's suspicious set" (BASELINE.json metric).
    """
    acc = None
    for r in range(n_runs):
        out = gibbs(counts, n_topics=n_topics, alpha=alpha, eta=eta,
                    n_sweeps=n_sweeps, burn_in=n_sweeps // 2,
                    seed=seed + 1000 * r, n_threads=n_threads)
        s = score_events_np(out["theta"], out["phi"], doc_ids, word_ids)
        logs = np.log(np.maximum(s, 1e-300))
        acc = logs if acc is None else acc + logs
    return np.exp(acc / n_runs)


# -- the judged comparison metric -----------------------------------------


def score_events_np(theta: np.ndarray, phi: np.ndarray,
                    doc_ids: np.ndarray, word_ids: np.ndarray) -> np.ndarray:
    """NumPy twin of onix.models.scoring.score_events (phi here is [K,V])."""
    return np.einsum("nk,nk->n", theta[doc_ids], phi.T[word_ids])


def topk_overlap(scores_a: np.ndarray, scores_b: np.ndarray, k: int) -> float:
    """|bottom-k(a) ∩ bottom-k(b)| / k — the suspicious-connect overlap.

    Bottom-k because LOW probability under the topic model == suspicious
    (SURVEY.md §2.1 #11).
    """
    a = np.argsort(scores_a, kind="stable")[:k]
    b = np.argsort(scores_b, kind="stable")[:k]
    return len(np.intersect1d(a, b)) / float(k)
