"""Corpus representation and synthetic generators.

The reference hands the LDA engine a text file in lda-c format —
`M term:count term:count ...` per document, one document per IP address
(SURVEY.md §2.1 #8, BASELINE.json "word-count build"). onix keeps the
corpus on-device as flat token arrays (`doc_ids`, `word_ids`), which is
the natural layout for a batched Gibbs sweep on TPU: every telemetry
event is exactly one token, so the token arrays ARE the event table and
per-event scoring needs no re-expansion.

Both views interconvert losslessly; the lda-c text format is kept for the
C++ oracle (native/lda_ref) and for parity with the reference's on-disk
contract (SURVEY.md §1 "Interfaces between layers are files").
"""

from __future__ import annotations

import dataclasses
import pathlib

import numpy as np


@dataclasses.dataclass
class Corpus:
    """Token-expanded corpus: one row per (document, token) pair.

    doc_ids[i] is the document (IP) of token i; word_ids[i] its word id.
    Documents and words are dense integer ids in [0, n_docs) / [0, n_vocab).
    """

    doc_ids: np.ndarray          # int32 [n_tokens]
    word_ids: np.ndarray         # int32 [n_tokens]
    n_docs: int
    n_vocab: int

    def __post_init__(self) -> None:
        self.doc_ids = np.asarray(self.doc_ids, dtype=np.int32)
        self.word_ids = np.asarray(self.word_ids, dtype=np.int32)
        if self.doc_ids.shape != self.word_ids.shape:
            raise ValueError("doc_ids and word_ids must have equal length")

    @property
    def n_tokens(self) -> int:
        return int(self.doc_ids.shape[0])

    # -- conversions ------------------------------------------------------

    def to_doc_word_counts(self) -> "SparseCounts":
        """Aggregate tokens into sparse (doc, word) -> count triples."""
        keys = self.doc_ids.astype(np.int64) * self.n_vocab + self.word_ids
        uniq, counts = np.unique(keys, return_counts=True)
        return SparseCounts(
            doc_ids=(uniq // self.n_vocab).astype(np.int32),
            word_ids=(uniq % self.n_vocab).astype(np.int32),
            counts=counts.astype(np.int32),
            n_docs=self.n_docs,
            n_vocab=self.n_vocab,
        )

    def doc_lengths(self) -> np.ndarray:
        return np.bincount(self.doc_ids, minlength=self.n_docs).astype(np.int32)

    def shuffled(self, seed: int = 0) -> "Corpus":
        """Random token permutation — decorrelates blocks within a Gibbs sweep."""
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self.n_tokens)
        return Corpus(self.doc_ids[perm], self.word_ids[perm],
                      self.n_docs, self.n_vocab)

    def padded(self, multiple: int) -> tuple["Corpus", np.ndarray]:
        """Pad token arrays to a multiple of `multiple` (static shapes for XLA).

        Returns (corpus, mask) where mask is 1.0 for real tokens. Padding
        tokens point at doc 0 / word 0 but carry zero weight everywhere.
        """
        n = self.n_tokens
        rem = (-n) % multiple
        if rem == 0:
            return self, np.ones(n, dtype=np.float32)
        doc = np.concatenate([self.doc_ids, np.zeros(rem, np.int32)])
        word = np.concatenate([self.word_ids, np.zeros(rem, np.int32)])
        mask = np.concatenate([np.ones(n, np.float32), np.zeros(rem, np.float32)])
        return Corpus(doc, word, self.n_docs, self.n_vocab), mask


@dataclasses.dataclass
class SparseCounts:
    """CSR-flavored sparse doc-word counts (the lda-c on-disk view)."""

    doc_ids: np.ndarray          # int32 [nnz], sorted by doc
    word_ids: np.ndarray         # int32 [nnz]
    counts: np.ndarray           # int32 [nnz]
    n_docs: int
    n_vocab: int

    @property
    def nnz(self) -> int:
        return int(self.doc_ids.shape[0])

    @property
    def n_tokens(self) -> int:
        return int(self.counts.sum())

    def to_tokens(self) -> Corpus:
        return Corpus(
            doc_ids=np.repeat(self.doc_ids, self.counts),
            word_ids=np.repeat(self.word_ids, self.counts),
            n_docs=self.n_docs,
            n_vocab=self.n_vocab,
        )

    # -- lda-c text format (reference contract; SURVEY.md §2.1 #9) --------

    def write_ldac(self, path: str | pathlib.Path) -> None:
        """Write `N w:c w:c ...` per document (docs with no tokens -> `0`)."""
        order = np.argsort(self.doc_ids, kind="stable")
        d, w, c = self.doc_ids[order], self.word_ids[order], self.counts[order]
        lines = []
        bounds = np.searchsorted(d, np.arange(self.n_docs + 1))
        for doc in range(self.n_docs):
            lo, hi = bounds[doc], bounds[doc + 1]
            parts = [str(hi - lo)]
            parts += [f"{w[i]}:{c[i]}" for i in range(lo, hi)]
            lines.append(" ".join(parts))
        pathlib.Path(path).write_text("\n".join(lines) + "\n")

    @staticmethod
    def read_ldac(path: str | pathlib.Path, n_vocab: int) -> "SparseCounts":
        docs, words, counts = [], [], []
        text = pathlib.Path(path).read_text().strip().splitlines()
        for doc, line in enumerate(text):
            parts = line.split()
            for entry in parts[1:]:
                w, _, c = entry.partition(":")
                docs.append(doc)
                words.append(int(w))
                counts.append(int(c))
        return SparseCounts(
            doc_ids=np.asarray(docs, np.int32),
            word_ids=np.asarray(words, np.int32),
            counts=np.asarray(counts, np.int32),
            n_docs=len(text),
            n_vocab=n_vocab,
        )


# -- synthetic corpora ----------------------------------------------------


def synthetic_lda_corpus(
    n_docs: int,
    n_vocab: int,
    n_topics: int,
    mean_doc_len: int = 100,
    alpha: float = 0.5,
    eta: float = 0.05,
    seed: int = 0,
) -> tuple[Corpus, np.ndarray, np.ndarray]:
    """Draw a corpus from the LDA generative model with known (theta, phi).

    Used by the numerical tests (SURVEY.md §4.2): an engine is correct if
    it recovers phi up to topic permutation. Returns (corpus, theta, phi)
    with theta [D,K], phi [K,V].
    """
    rng = np.random.default_rng(seed)
    phi = rng.dirichlet(np.full(n_vocab, eta), size=n_topics)       # [K,V]
    theta = rng.dirichlet(np.full(n_topics, alpha), size=n_docs)    # [D,K]
    doc_lens = rng.poisson(mean_doc_len, size=n_docs).clip(min=1)
    doc_ids = np.repeat(np.arange(n_docs, dtype=np.int32), doc_lens)
    # Vectorized ancestral sampling: z ~ Cat(theta[d]), w ~ Cat(phi[z]).
    u = rng.random(doc_ids.shape[0])
    z = (theta.cumsum(axis=1)[doc_ids] < u[:, None]).sum(axis=1).astype(np.int32)
    z = z.clip(max=n_topics - 1)
    u2 = rng.random(doc_ids.shape[0])
    word_ids = np.empty_like(doc_ids)
    phi_cum = phi.cumsum(axis=1)
    for k in range(n_topics):   # K is small (default 20) — loop over topics only
        sel = z == k
        word_ids[sel] = np.searchsorted(phi_cum[k], u2[sel], side="right")
    word_ids = word_ids.clip(max=n_vocab - 1).astype(np.int32)
    return Corpus(doc_ids, word_ids, n_docs, n_vocab), theta, phi


def anomaly_corpus(
    n_docs: int = 200,
    n_vocab: int = 400,
    n_topics: int = 10,
    mean_doc_len: int = 200,
    n_anomalies: int = 25,
    seed: int = 0,
) -> tuple[Corpus, np.ndarray]:
    """Synthetic corpus with planted rare events — the suspicious-connects
    shape (reference README.md:42 "filter billion of events to a few
    thousands"). Returns (corpus, anomaly_token_idx): the planted tokens
    use words drawn uniformly from the rarest decile of the vocabulary in
    documents whose topic mixture never emits them.
    """
    corpus, theta, phi = synthetic_lda_corpus(
        n_docs, n_vocab, n_topics, mean_doc_len, seed=seed)
    rng = np.random.default_rng(seed + 1)
    # Words with the lowest total probability across all topics.
    rare_words = np.argsort(phi.sum(axis=0))[: max(n_vocab // 10, n_anomalies)]
    idx = rng.choice(corpus.n_tokens, size=n_anomalies, replace=False)
    corpus.word_ids[idx] = rng.choice(rare_words, size=n_anomalies).astype(np.int32)
    return corpus, np.sort(idx)
